"""Greedy coordinate-descent autotune controller.

The loop ISSUE 16 names, encoded::

    measure incumbent → doctor ranks bottlenecks → top verdict's
    structured action names ONE knob axis → trial that axis's
    candidates → accept only a measured improvement beyond the noise
    floor → commit the winner to the tuning table with provenance →
    re-diagnose from the new incumbent → repeat until no verdict
    offers an untried move.

Cost model: a full sweep enumerates |grid| = Π|axis| configurations;
this controller measures O(knobs-that-matter) — only axes the doctor
actually implicates, and within an axis only candidates not yet tried
(a rejected (axis, value) pair is NEVER revisited, so the trial count
is bounded by the total candidate count even on a noisy objective).

Safety rails (all contract-tested):

- every measurement runs inside a flight-recorder-annotated TRIAL
  WINDOW bracketed by XLA compile-counter snapshots;
- a trial that errors (watchdog raise included), recompile-storms
  (post-warmup compiles in the measured window beyond the budget), or
  REGRESSES beyond the noise floor is rolled back to the incumbent
  config and dumped as an ``autotune-rollback`` flightrec bundle;
- compiles observed OUTSIDE trial windows are tallied and reported
  (``compiles_outside_trials``) so the zero-recompile-outside-trials
  contract is checkable by the caller.

The controller owns NO measurement code: ``measure(config) -> row`` is
injected (bench.py's ``--autotune`` mode wraps ``bench_train`` +
``_retry_transient`` + BENCH_RUN-keyed resume; tests inject synthetic
objective surfaces).  The row must carry the objective under
``objective_key``; ``doctor`` (ranked verdicts) and
``xla_compiles_measured`` ride along when available.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..observability import flightrec as _flightrec
from ..utils import compile_counter as _cc
from ..utils import tuning as _tuning
from .knobs import AXES, KnobAxis, axis_for_action

__all__ = ["AutotuneController", "noise_floor_default"]


def noise_floor_default() -> float:
    """Relative improvement a trial must beat to be accepted (2%
    default; PADDLE_TPU_AUTOTUNE_NOISE overrides)."""
    try:
        return float(os.environ.get("PADDLE_TPU_AUTOTUNE_NOISE", "0.02"))
    except ValueError:
        return 0.02


class AutotuneController:
    """One greedy coordinate-descent pass over a knob space.

    Parameters
    ----------
    measure:
        ``measure(config: dict) -> row: dict``.  Must return the
        objective under ``objective_key``; may raise (the trial is then
        rolled back).  Resume/retry belong INSIDE measure (bench.py
        wraps ``_retry_transient`` + persisted-row lookup).
    kind:
        'train' | 'serve' — restricts both the doctor rule table and
        the eligible knob axes.
    objective_key / maximize:
        which row field is the objective and its direction (MFU: up;
        a latency: down).
    noise_floor:
        relative improvement an acceptance must exceed; a trial WORSE
        than the incumbent by more than this is a regression (rollback
        + flightrec bundle), in between is an indifferent reject.
    commit_keys:
        ``{param: (table_op, key_tuple)}`` — where an accepted value
        for that axis persists in the unified tuning table.  Supplied
        by the embedder (it knows the model/device identity); axes
        absent from the map are accepted in-config but not persisted.
    storm_compiles:
        measured-window compile budget per trial; a row whose
        ``xla_compiles_measured`` exceeds it is a recompile-storm
        (rollback + bundle).  Default 0 — a MEASURED window is
        post-warmup by construction, so any compile inside it is churn.
    axes:
        eligible axis names (default: every registry axis matching
        ``kind``).
    """

    def __init__(self, measure: Callable[[dict], dict], *,
                 kind: str = "train", objective_key: str = "mfu",
                 maximize: bool = True,
                 noise_floor: Optional[float] = None,
                 max_trials: Optional[int] = None,
                 run_id: str = "",
                 commit_keys: Optional[Dict[str, Tuple[str, tuple]]] = None,
                 storm_compiles: int = 0,
                 axes: Optional[List[str]] = None,
                 log: Optional[Callable[[str], None]] = None):
        self.measure = measure
        self.kind = kind
        self.objective_key = objective_key
        self.maximize = bool(maximize)
        self.noise_floor = noise_floor_default() if noise_floor is None \
            else float(noise_floor)
        self.run_id = run_id or os.environ.get("BENCH_RUN", "") or \
            "autotune"
        self.commit_keys = dict(commit_keys or {})
        self.storm_compiles = int(storm_compiles)
        names = axes if axes is not None else \
            [n for n, a in AXES.items() if kind in a.kinds]
        self.axes: Dict[str, KnobAxis] = \
            {n: AXES[n] for n in names if n in AXES}
        self.max_trials = int(max_trials) if max_trials is not None \
            else max(4, 3 * len(self.axes))
        self._log = log or (lambda s: None)
        # (axis, repr(value)) pairs ever trialed — accepted or rejected,
        # a pair is never measured twice (the never-revisit contract)
        self._tried: set = set()
        self.trials: List[dict] = []
        self.committed: List[dict] = []
        self._in_trial_compiles = 0

    # ------------------------------------------------------------------
    def _objective(self, row: Optional[dict]) -> Optional[float]:
        if not isinstance(row, dict):
            return None
        v = row.get(self.objective_key)
        return float(v) if isinstance(v, (int, float)) and not \
            isinstance(v, bool) else None

    def _improvement(self, incumbent: float, trial: float) -> float:
        """Signed relative improvement of trial over incumbent (>0 is
        better regardless of objective direction)."""
        if incumbent == 0:
            return 0.0
        rel = (trial - incumbent) / abs(incumbent)
        return rel if self.maximize else -rel

    def _verdicts(self, row: dict) -> List[dict]:
        v = row.get("doctor")
        if isinstance(v, list):
            return v
        from ..observability import doctor as _doctor
        try:
            return _doctor.diagnose(row, self.kind)
        except Exception:
            return []

    def _measure_window(self, config: dict, label: str) -> tuple:
        """One measurement inside a flight-recorder trial window
        bracketed by compile snapshots. Returns (row | None, compiles,
        error | None)."""
        _flightrec.note_event("autotune_trial", run=self.run_id,
                              label=label,
                              trial=len(self.trials))
        snap = _cc.snapshot()
        try:
            row = self.measure(dict(config))
            err = None
        except Exception as e:           # watchdog raise lands here too
            row, err = None, f"{type(e).__name__}: {e}"
        compiles = snap.new_compiles
        self._in_trial_compiles += compiles
        return row, compiles, err

    def _rollback(self, axis_name: str, value, reason: str,
                  detail: dict) -> None:
        """A failed trial: the incumbent stays, the evidence ships as a
        flight-recorder bundle (dump() never raises, caps itself)."""
        self._log(f"autotune: rollback {axis_name}={value!r} ({reason})")
        _flightrec.dump("autotune-rollback",
                        extra={"autotune": dict(detail, axis=axis_name,
                                                value=repr(value),
                                                reason=reason,
                                                run=self.run_id)})

    def _commit(self, axis: KnobAxis, value, improvement: float) -> None:
        """Persist an accepted winner into the unified tuning table with
        provenance (embedder-supplied key; no key → config-only win)."""
        dest = self.commit_keys.get(axis.name)
        if not dest:
            return
        op, key = dest
        _tuning.record(op, key, value, source="autotune",
                       run=self.run_id, improvement=improvement)
        self.committed.append({"op": op, "key": list(map(str, key)),
                               "value": value,
                               "improvement": round(improvement, 6)})
        self._log(f"autotune: committed {op}|{'|'.join(map(str, key))}"
                  f" = {value!r} (+{improvement * 100:.2f}%)")

    # ------------------------------------------------------------------
    def _candidate_moves(self, config: dict, verdicts: List[dict]):
        """Yield (axis, value, bottleneck) moves in verdict-rank order,
        untried pairs only — the coordinate-descent frontier."""
        for v in verdicts:
            axis = axis_for_action(v.get("action"))
            if axis is None or axis.name not in self.axes:
                continue
            suggested = (v.get("action") or {}).get("candidates")
            for val in axis.trial_values(config.get(axis.name),
                                         suggested):
                if (axis.name, repr(val)) in self._tried:
                    continue
                yield axis, val, v.get("bottleneck", "?")

    def run(self, base_config: dict) -> dict:
        """One full pass from ``base_config``; returns the summary dict
        (winning config, trial log, compile accounting)."""
        run_snap = _cc.snapshot()
        self._in_trial_compiles = 0
        incumbent = dict(base_config)
        inc_row, _, err = self._measure_window(incumbent, "incumbent")
        inc_obj = self._objective(inc_row)
        if inc_obj is None:
            return {"run": self.run_id, "error": err or
                    f"incumbent row lacks {self.objective_key!r}",
                    "config": incumbent, "trials": [],
                    "measured_trials": 0, "committed": [],
                    "compiles_outside_trials": 0, "converged": False}
        baseline_obj = inc_obj
        converged = False
        while len(self.trials) < self.max_trials:
            moved = False
            for axis, val, bottleneck in self._candidate_moves(
                    incumbent, self._verdicts(inc_row)):
                self._tried.add((axis.name, repr(val)))
                trial_cfg = dict(incumbent)
                trial_cfg[axis.name] = val
                row, compiles, err = self._measure_window(
                    trial_cfg, f"{axis.name}={val!r}")
                obj = self._objective(row)
                rec = {"axis": axis.name, "value": val,
                       "bottleneck": bottleneck,
                       "objective": obj, "compiles": compiles,
                       "incumbent_objective": inc_obj}
                if err is not None:
                    rec.update(outcome="rollback", reason="error",
                               error=err)
                    self._rollback(axis.name, val, "error", rec)
                elif obj is None:
                    rec.update(outcome="reject", reason="no-objective")
                elif row.get("xla_compiles_measured", 0) > \
                        self.storm_compiles:
                    rec.update(outcome="rollback",
                               reason="recompile-storm",
                               xla_compiles_measured=row[
                                   "xla_compiles_measured"])
                    self._rollback(axis.name, val, "recompile-storm",
                                   rec)
                else:
                    imp = self._improvement(inc_obj, obj)
                    rec["improvement"] = round(imp, 6)
                    if imp > self.noise_floor:
                        rec["outcome"] = "accept"
                        incumbent, inc_row, inc_obj = trial_cfg, row, obj
                        self._commit(axis, val, imp)
                        self._log(f"autotune: accept {axis.name}="
                                  f"{val!r} ({self.objective_key} "
                                  f"{inc_obj:.4g}, +{imp * 100:.2f}%)")
                    elif imp < -self.noise_floor:
                        rec.update(outcome="rollback",
                                   reason="regression")
                        self._rollback(axis.name, val, "regression",
                                       rec)
                    else:
                        rec.update(outcome="reject",
                                   reason="within-noise")
                self.trials.append(rec)
                if rec.get("outcome") == "accept" or \
                        len(self.trials) >= self.max_trials:
                    moved = rec.get("outcome") == "accept"
                    break
            else:
                # no verdict offered an untried move: descent is done
                converged = True
            if converged:
                break
            if not moved and len(self.trials) < self.max_trials:
                # the frontier existed but every move failed — the for
                # loop above only breaks on accept/budget; reaching
                # here without `moved` means the frontier is exhausted
                converged = True
                break
        total = run_snap.new_compiles
        return {"run": self.run_id, "objective": self.objective_key,
                "baseline": baseline_obj, "best": inc_obj,
                "improvement": round(
                    self._improvement(baseline_obj, inc_obj), 6),
                "config": incumbent,
                "trials": self.trials,
                "measured_trials": len(self.trials),
                "accepted": sum(1 for t in self.trials
                                if t.get("outcome") == "accept"),
                "rolled_back": sum(1 for t in self.trials
                                   if t.get("outcome") == "rollback"),
                "committed": self.committed,
                "compiles_total": total,
                "compiles_outside_trials": max(
                    0, total - self._in_trial_compiles),
                "converged": converged}
