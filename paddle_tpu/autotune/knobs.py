"""Knob-axis registry: the vocabulary shared by doctor actions and the
autotune controller.

A doctor verdict's structured ``action`` names a ``param`` — the config
axis to mutate.  This module maps that name to a :class:`KnobAxis`
carrying everything the controller needs to trial it: which benchmark
kinds it applies to, the default candidate values when the action does
not supply its own, the equivalent env knob, and the tuning-table op a
winner commits under.  One registry, so the doctor, the offline
controller, the live retuner and the report CLI all agree on what a
knob IS — nobody string-parses advice (ISSUE 16 satellite).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["KnobAxis", "AXES", "axis_for", "axis_for_action"]


class KnobAxis:
    """One tunable coordinate: name == the config/param key the measure
    harness understands.  ``candidates`` are the default trial values
    (a doctor action's non-empty candidate list overrides them);
    ``table_op`` is the unified-tuning-table namespace a winner commits
    under (None: env/config-only knob, nothing to persist)."""

    def __init__(self, name: str, kinds: Tuple[str, ...],
                 candidates: Sequence[Any] = (),
                 env: Optional[str] = None,
                 table_op: Optional[str] = None,
                 hot_apply: bool = False):
        self.name = name
        self.kinds = kinds
        self.candidates = list(candidates)
        self.env = env
        self.table_op = table_op
        # hot_apply: mutating this knob on a LIVE engine is a host-side
        # table/config change only — no retrace, no recompile — so the
        # live retuner may apply it without a restart
        self.hot_apply = hot_apply

    def trial_values(self, incumbent: Any,
                     suggested: Optional[Sequence[Any]] = None
                     ) -> List[Any]:
        """Candidate values to trial, the action's suggestion winning
        over the axis defaults, minus the incumbent value itself."""
        vals = list(suggested) if suggested else list(self.candidates)
        return [v for v in vals if v != incumbent]

    def __repr__(self):  # pragma: no cover - debug aid
        return f"KnobAxis({self.name!r}, kinds={self.kinds})"


# the registry: every axis ISSUE 16 names, keyed by param name.  Train
# axes mirror bench.py's bench_train() signature; serve axes mirror
# InferenceEngine construction knobs.
AXES: Dict[str, KnobAxis] = {a.name: a for a in [
    # -- train ----------------------------------------------------------
    KnobAxis("remat_policy", ("train",),
             candidates=["off", "dots_no_batch", "dots", "full"],
             table_op="remat_policy"),
    KnobAxis("quantize", ("train",),
             candidates=[None, "int8"], env="BENCH_QUANTIZE",
             table_op="qmm_tiles"),
    KnobAxis("use_flash", ("train",),
             candidates=[True, False], table_op="flash_blocks"),
    KnobAxis("scan", ("train",), candidates=[True, False]),
    KnobAxis("overlap", ("train",), candidates=[True, False],
             env="PADDLE_TPU_OVERLAP"),
    KnobAxis("moe_a2a_chunks", ("train",), candidates=[1, 2, 4, 8],
             env="PADDLE_TPU_MOE_A2A_CHUNKS",
             table_op="moe_a2a_chunks"),
    KnobAxis("prefetch_depth", ("train",), candidates=[0, 2, 4, 8],
             env="PADDLE_TPU_PREFETCH_DEPTH"),
    # -- serve ----------------------------------------------------------
    KnobAxis("spec_k", ("serve",), candidates=[0, 2, 4],
             env="PADDLE_TPU_SPEC_K"),
    KnobAxis("kv_dtype", ("serve",), candidates=["dense", "int8"],
             env="PADDLE_TPU_KV_DTYPE"),
    KnobAxis("decode_megakernel", ("serve",), candidates=[False, True],
             env="PADDLE_TPU_DECODE_MEGAKERNEL",
             table_op="megakernel_blocks"),
    KnobAxis("megakernel_blocks", ("serve",), candidates=[],
             env="PADDLE_TPU_MEGAKERNEL_BLOCKS",
             table_op="megakernel_blocks"),
    KnobAxis("prefill_buckets", ("serve",), candidates=[],
             env="PADDLE_TPU_PREFILL_BUCKETS",
             table_op="prefill_buckets", hot_apply=True),
    KnobAxis("qmm_tiles", ("train", "serve"), candidates=[],
             table_op="qmm_tiles"),
    KnobAxis("flash_blocks", ("train", "serve"), candidates=[],
             table_op="flash_blocks"),
    KnobAxis("batch_slots", ("serve",), candidates=[],
             env="PADDLE_TPU_DECODE_SLOTS"),
    KnobAxis("prefix_cache", ("serve",), candidates=[True],
             env="PADDLE_TPU_PREFIX_CACHE"),
    # chunked prefill (ISSUE 20): 0 disables; hot_apply via
    # InferenceEngine.set_prefill_chunk — a host-side flag flip (the
    # chunk executable for a NEW width compiles once, at apply time,
    # not in the steady-state serving loop)
    KnobAxis("prefill_chunk", ("serve",), candidates=[0, 32, 64, 128],
             env="PADDLE_TPU_CHUNKED_PREFILL", hot_apply=True),
]}


def axis_for(param: Optional[str]) -> Optional[KnobAxis]:
    """Registry lookup by param name (None/unknown -> None)."""
    if not param:
        return None
    return AXES.get(param)


def axis_for_action(action: Optional[dict]) -> Optional[KnobAxis]:
    """The axis a doctor verdict's structured action points at — None
    for behavioral advice (param None) or an unknown param (a future
    doctor rule must not crash an old controller)."""
    if not isinstance(action, dict):
        return None
    return axis_for(action.get("param"))
