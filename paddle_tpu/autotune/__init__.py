"""Doctor-driven autotune: close the perf loop from verdict to knob to
tuning table (ISSUE 16, ROADMAP item 1).

The observability tier (PRs 13-15) ends every run with a ranked doctor
verdict and a per-executable MFU gap-attribution — this package ACTS on
them.  Three tiers behind one env knob, ``PADDLE_TPU_AUTOTUNE``:

- ``off`` (default) — nothing armed; sweeps and humans turn knobs.
- ``once`` — an offline greedy coordinate-descent pass
  (:class:`~paddle_tpu.autotune.controller.AutotuneController`, driven
  by ``bench.py --autotune``): measure the incumbent, follow the
  doctor's top verdict to exactly ONE knob axis, trial its candidates,
  accept only a measured improvement beyond the noise floor, commit the
  winner into the unified tuning table with provenance, re-diagnose,
  repeat — O(knobs-that-matter) measurements instead of |grid|.
- ``live`` — the controller's safety-railed sibling inside a serving
  engine (:class:`~paddle_tpu.autotune.live.LiveRetuner`): an
  SLO-regression signal schedules exactly one retune episode, the
  episode waits for a quiesced replica (no active slots, empty queue),
  re-measures table-only knobs between decode-step windows on already
  warmed executables (zero recompiles), and hot-applies the winner.

Every trial runs under the flight recorder; a trial that regresses,
recompile-storms, or trips the watchdog is rolled back to the incumbent
config and dumped as a ``autotune-rollback`` bundle.
"""
from __future__ import annotations

import os

from .knobs import AXES, KnobAxis, axis_for, axis_for_action  # noqa: F401
from .controller import AutotuneController  # noqa: F401

__all__ = ["AutotuneController", "AXES", "KnobAxis", "axis_for",
           "axis_for_action", "autotune_mode"]


def autotune_mode() -> str:
    """The PADDLE_TPU_AUTOTUNE tier: 'off' | 'once' | 'live' (anything
    unrecognized reads as 'off' — a typo must not arm a retuner)."""
    v = os.environ.get("PADDLE_TPU_AUTOTUNE", "").strip().lower()
    return v if v in ("once", "live") else "off"
