"""Activation functionals.

Reference parity: /root/reference/paddle/fluid/operators/activation_op.cc
(REGISTER_ACTIVATION_OP list) and python/paddle/nn/functional/activation.py.
Each is a jnp/jax.nn lowering; XLA fuses them into neighboring matmuls so
there is no need for the reference's fused activation kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply

__all__ = [
    "relu", "relu6", "relu_", "elu", "elu_", "selu", "celu", "gelu", "sigmoid",
    "hardsigmoid", "hardswish", "hardtanh", "hardshrink", "softshrink",
    "tanhshrink", "leaky_relu", "log_sigmoid", "log_softmax", "softmax",
    "softmax_", "softplus", "softsign", "swish", "silu", "mish", "tanh",
    "tanh_", "thresholded_relu", "maxout", "prelu", "glu", "rrelu",
    "gumbel_softmax",
]


def relu(x, name=None):
    return apply(jax.nn.relu, x, name="relu")


def _inplace(x, op):
    """Paddle in-place semantics on the tape: run the op on a detached
    alias of x (same data + same creator) and rebind x to the result, so
    the recorded node's input is NOT x itself (which would create a cycle
    in the tape)."""
    from ...core.tensor import Tensor

    alias = Tensor(x._data, stop_gradient=x.stop_gradient,
                   _creator=x._creator, name=x.name)
    out = op(alias)
    x._data = out._data
    x._creator = out._creator
    x.stop_gradient = out.stop_gradient
    return x


def relu_(x, name=None):
    return _inplace(x, relu)


def relu6(x, name=None):
    return apply(lambda a: jnp.clip(a, 0.0, 6.0), x, name="relu6")


def elu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.elu(a, alpha), x, name="elu")


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply(lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
                 x, name="selu")


def celu(x, alpha=1.0, name=None):
    return apply(lambda a: jax.nn.celu(a, alpha), x, name="celu")


def gelu(x, approximate=False, name=None):
    return apply(lambda a: jax.nn.gelu(a, approximate=approximate), x, name="gelu")


def sigmoid(x, name=None):
    return apply(jax.nn.sigmoid, x, name="sigmoid")


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply(lambda a: jnp.clip(slope * a + offset, 0.0, 1.0),
                 x, name="hardsigmoid")


def hardswish(x, name=None):
    return apply(lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0,
                 x, name="hardswish")


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply(lambda a: jnp.clip(a, min, max), x, name="hardtanh")


def hardshrink(x, threshold=0.5, name=None):
    return apply(lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0),
                 x, name="hardshrink")


def softshrink(x, threshold=0.5, name=None):
    return apply(
        lambda a: jnp.where(a > threshold, a - threshold,
                            jnp.where(a < -threshold, a + threshold, 0.0)),
        x, name="softshrink")


def tanhshrink(x, name=None):
    return apply(lambda a: a - jnp.tanh(a), x, name="tanhshrink")


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply(lambda a: jax.nn.leaky_relu(a, negative_slope),
                 x, name="leaky_relu")


def log_sigmoid(x, name=None):
    return apply(jax.nn.log_sigmoid, x, name="log_sigmoid")


def log_softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.log_softmax(a, axis=axis)
    return apply(fn, x, name="log_softmax")


def softmax(x, axis=-1, dtype=None, name=None):
    def fn(a):
        if dtype is not None:
            from ...core.dtype import convert_dtype
            a = a.astype(convert_dtype(dtype))
        return jax.nn.softmax(a, axis=axis)
    return apply(fn, x, name="softmax")


def softmax_(x, axis=-1, dtype=None, name=None):
    return _inplace(x, lambda a: softmax(a, axis=axis, dtype=dtype))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply(
        lambda a: jnp.where(beta * a > threshold, a,
                            jnp.logaddexp(beta * a, 0.0) / beta),
        x, name="softplus")


def softsign(x, name=None):
    return apply(jax.nn.soft_sign, x, name="softsign")


def swish(x, name=None):
    return apply(jax.nn.silu, x, name="swish")


silu = swish


def mish(x, name=None):
    return apply(lambda a: a * jnp.tanh(jax.nn.softplus(a)), x, name="mish")


def tanh(x, name=None):
    return apply(jnp.tanh, x, name="tanh")


def tanh_(x, name=None):
    return _inplace(x, tanh)


def thresholded_relu(x, threshold=1.0, name=None):
    return apply(lambda a: jnp.where(a > threshold, a, 0.0),
                 x, name="thresholded_relu")


def maxout(x, groups, axis=1, name=None):
    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        c = a.shape[ax]
        shape = a.shape[:ax] + (c // groups, groups) + a.shape[ax + 1:]
        return jnp.max(a.reshape(shape), axis=ax + 1)
    return apply(fn, x, name="maxout")


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(a, w):
        if w.size == 1:
            wb = w.reshape(())
        else:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
            shape[ch_axis] = w.size
            wb = w.reshape(shape)
        return jnp.where(a >= 0, a, wb * a)
    return apply(fn, x, weight, name="prelu")


def glu(x, axis=-1, name=None):
    return apply(lambda a: jax.nn.glu(a, axis=axis), x, name="glu")


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    # Evaluation-mode deterministic form; training form uses the mean slope
    # (matches the reference's expectation in eval; random slopes are a
    # regularizer detail).
    slope = (lower + upper) / 2.0
    return apply(lambda a: jnp.where(a >= 0, a, slope * a), x, name="rrelu")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core import random as prandom

    key = prandom.next_key()

    def fn(a):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(key, a.shape, a.dtype, 1e-20, 1.0) + 1e-20))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            # straight-through: forward one-hot, backward soft
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.put_along_axis(jnp.zeros_like(y), idx, 1.0,
                                        axis=axis, inplace=False)
            y = y + jax.lax.stop_gradient(y_hard - y)
        return y
    return apply(fn, x, name="gumbel_softmax")


def elu_(x, alpha=1.0, name=None):
    """In-place elu (reference elu_)."""
    return _inplace(x, lambda a: elu(a, alpha=alpha))
