"""Normalization functionals.

Reference parity: /root/reference/paddle/fluid/operators/batch_norm_op.cc,
layer_norm_op.cc, instance_norm_op.cc, group_norm_op.cc, norm_op.cc and
python/paddle/nn/functional/norm.py. Batch statistics are computed inline
(one fused XLA reduction) — no cuDNN batch-norm descriptors. The
distributed SyncBatchNorm variant lives in paddle_tpu.distributed (psum
over the dp axis replaces the reference's sync_batch_norm_op.cu NCCL
allreduce of statistics).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply
from ...core.tensor import Tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "normalize", "local_response_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Functional batch norm. In training mode the running stats tensors
    are UPDATED IN PLACE on the host side (matching the reference's
    mean_out/variance_out aliasing, batch_norm_op.cc)."""
    channel_last = not data_format.startswith("NC")
    use_batch_stats = training and not use_global_stats

    def stats_axes(a):
        ch_axis = a.ndim - 1 if channel_last else min(1, a.ndim - 1)
        return tuple(i for i in range(a.ndim) if i != ch_axis), ch_axis

    if use_batch_stats:
        # batch stats recomputed eagerly ONLY for the running update; the
        # differentiated fn below recomputes them from the traced input so
        # jax.vjp carries the d(mean)/dx and d(var)/dx terms (reference
        # batch_norm_grad_op semantics)
        xa = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        axes, ch_axis = stats_axes(xa)
        bm = jnp.mean(xa.astype(jnp.float32), axis=axes)
        bv = jnp.var(xa.astype(jnp.float32), axis=axes)
        if isinstance(running_mean, Tensor):
            running_mean._data = (momentum * running_mean.data +
                                  (1 - momentum) * bm).astype(
                                      running_mean.data.dtype)
            running_var._data = (momentum * running_var.data +
                                 (1 - momentum) * bv).astype(
                                     running_var.data.dtype)

    has_w, has_b = weight is not None, bias is not None

    def fn_batch(a, *rest):
        axes, ch_axis = stats_axes(a)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=axes).reshape(shape)
        v = jnp.var(af, axis=axes).reshape(shape)
        out = (af - m) * jax.lax.rsqrt(v + epsilon)
        it = iter(rest)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return out.astype(a.dtype)

    def fn_global(a, m, v, *rest):
        axes, ch_axis = stats_axes(a)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        m = m.reshape(shape).astype(jnp.float32)
        v = v.reshape(shape).astype(jnp.float32)
        out = (a.astype(jnp.float32) - m) * jax.lax.rsqrt(v + epsilon)
        it = iter(rest)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return out.astype(a.dtype)

    if use_batch_stats:
        args = [x]
        fn = fn_batch
    else:
        args = [x, running_mean, running_var]
        fn = fn_global
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply(fn, *args, name="batch_norm")


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))

    has_w, has_b = weight is not None, bias is not None

    def fn(a, *rest):
        axes = tuple(range(a.ndim - n_axes, a.ndim))
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=axes, keepdims=True)
        v = jnp.var(af, axis=axes, keepdims=True)
        out = (af - m) * jax.lax.rsqrt(v + epsilon)
        it = iter(rest)
        if has_w:
            out = out * next(it)
        if has_b:
            out = out + next(it)
        return out.astype(a.dtype)

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply(fn, *args, name="layer_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")

    has_w, has_b = weight is not None, bias is not None

    def fn(a, *rest):
        if channel_last:
            axes = tuple(range(1, a.ndim - 1))
            ch_axis = a.ndim - 1
        else:
            axes = tuple(range(2, a.ndim))
            ch_axis = 1
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=axes, keepdims=True)
        v = jnp.var(af, axis=axes, keepdims=True)
        out = (af - m) * jax.lax.rsqrt(v + eps)
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        it = iter(rest)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        return out.astype(a.dtype)

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply(fn, *args, name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = not data_format.startswith("NC")
    has_w, has_b = weight is not None, bias is not None

    def fn(a, *rest):
        if channel_last:
            a_nc = jnp.moveaxis(a, -1, 1)
        else:
            a_nc = a
        n, c = a_nc.shape[:2]
        spatial = a_nc.shape[2:]
        g = a_nc.reshape(n, num_groups, c // num_groups, *spatial)
        gf = g.astype(jnp.float32)
        axes = tuple(range(2, gf.ndim))
        m = jnp.mean(gf, axis=axes, keepdims=True)
        v = jnp.var(gf, axis=axes, keepdims=True)
        out = ((gf - m) * jax.lax.rsqrt(v + epsilon)).reshape(a_nc.shape)
        shape = [1, c] + [1] * len(spatial)
        it = iter(rest)
        if has_w:
            out = out * next(it).reshape(shape)
        if has_b:
            out = out + next(it).reshape(shape)
        out = out.astype(a.dtype)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply(fn, *args, name="group_norm")


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(a):
        norm = jnp.sum(jnp.abs(a) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return a / jnp.maximum(norm, epsilon)
    return apply(fn, x, name="normalize")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    """reference lrn_op.cc."""
    channel_last = not data_format.startswith("NC")

    def fn(a):
        ch_axis = a.ndim - 1 if channel_last else 1
        sq = jnp.square(a.astype(jnp.float32))
        sq = jnp.moveaxis(sq, ch_axis, -1)
        pad = (size - 1) // 2
        sq_p = jnp.pad(sq, [(0, 0)] * (sq.ndim - 1) +
                       [(pad, size - 1 - pad)])
        win = sum(sq_p[..., i:i + sq.shape[-1]] for i in range(size))
        div = (k + alpha * win / size) ** beta
        div = jnp.moveaxis(div, -1, ch_axis)
        return (a / div).astype(a.dtype)

    return apply(fn, x, name="local_response_norm")
