"""Loss functionals.

Reference parity: /root/reference/paddle/fluid/operators/
softmax_with_cross_entropy_op.cc, cross_entropy_op.cc, bce_loss_op.cc,
smooth_l1_loss_op.cc, kldiv_loss_op.cc, margin_rank_loss_op.cc, ... and
python/paddle/nn/functional/loss.py. Every loss is a fused jnp expression
(log_softmax + gather beats the reference's separate softmax/CE kernels —
XLA fuses the whole thing into one pass).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.autograd import apply
from ...core.tensor import Tensor

__all__ = [
    "cross_entropy", "fused_linear_cross_entropy",
    "softmax_with_cross_entropy", "binary_cross_entropy",
    "binary_cross_entropy_with_logits", "mse_loss", "l1_loss", "nll_loss",
    "kl_div", "smooth_l1_loss", "margin_ranking_loss", "hinge_embedding_loss",
    "cosine_embedding_loss", "ctc_loss", "log_loss", "square_error_cost",
    "sigmoid_focal_loss", "triplet_margin_loss", "dice_loss",
    "hsigmoid_loss",
    "npair_loss",
]


def _reduce(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference softmax_with_cross_entropy_op.cc semantics + paddle 2.x
    cross_entropy wrapper."""

    def fn(logits, lab, *rest):
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-15, 1.0))
        if soft_label:
            tgt = lab.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = (1 - label_smoothing) * tgt + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
        else:
            lab_i = lab.astype(jnp.int32)
            if lab_i.ndim == logp.ndim:
                lab_i = jnp.squeeze(lab_i, axis=axis)
            if label_smoothing > 0:
                k = logits.shape[axis]
                onehot = jax.nn.one_hot(lab_i, k, axis=axis)
                tgt = (1 - label_smoothing) * onehot + label_smoothing / k
                loss = -jnp.sum(tgt * logp, axis=axis)
            else:
                safe = jnp.where(lab_i == ignore_index, 0, lab_i)
                gathered = jnp.take_along_axis(
                    logp, jnp.expand_dims(safe, axis), axis=axis)
                loss = -jnp.squeeze(gathered, axis=axis)
            mask = (lab_i != ignore_index)
            loss = jnp.where(mask, loss, 0.0)
            if rest:
                w = rest[0]
                wl = jnp.take(w, jnp.where(lab_i == ignore_index, 0, lab_i))
                wl = jnp.where(mask, wl, 0.0)
                loss = loss * wl
                if reduction == "mean":
                    return jnp.sum(loss) / jnp.maximum(jnp.sum(wl), 1e-12)
            if reduction == "mean":
                denom = jnp.maximum(jnp.sum(mask.astype(jnp.float32)), 1.0)
                return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label]
    if weight is not None:
        args.append(weight)
    return apply(fn, *args, name="cross_entropy")


def fused_linear_cross_entropy(input, weight, label, ignore_index=-100,
                               reduction="mean", block_size=None,
                               name=None):
    """Cross-entropy of `input @ weight.T` against integer labels,
    computed blockwise over the vocab (ops.fused_cross_entropy) so the
    [N, V] logits tensor is never materialized in forward or backward —
    the LM-head loss for large vocabularies. input [N, H]; weight
    [V, H] (embedding layout, i.e. the tied LM head); label [N].
    Matches cross_entropy(soft_label=False) loss and gradients."""
    from ...ops.fused_cross_entropy import \
        fused_linear_cross_entropy as _op

    def fn(x, w, lab):
        return _op(x, w, lab, ignore_index=ignore_index,
                   reduction=reduction, block_size=block_size)

    return apply(fn, input, weight, label,
                 name="fused_linear_cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, numeric_stable_mode=True,
                               return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none",
                         axis=axis)
    # the raw op keeps the label dim (N,1)
    if not soft_label:
        lab_ndim = len(label.shape) if isinstance(label, Tensor) else label.ndim
        if len(loss.shape) < lab_ndim:
            from ...tensor.manipulation import unsqueeze
            loss = unsqueeze(loss, axis)
    if return_softmax:
        from .activation import softmax as softmax_fn
        return loss, softmax_fn(logits, axis=axis)
    return loss


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    def fn(p, t, *rest):
        p = jnp.clip(p.astype(jnp.float32), 1e-12, 1.0 - 1e-12)
        out = -(t * jnp.log(p) + (1 - t) * jnp.log(1 - p))
        if rest:
            out = out * rest[0]
        return _reduce(out, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply(fn, *args, name="binary_cross_entropy")


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def fn(z, t, *rest):
        zf = z.astype(jnp.float32)
        tf_ = t.astype(jnp.float32)
        # stable: max(z,0) - z*t + log(1+exp(-|z|)); pos_weight scales the
        # positive term like the reference sigmoid_cross_entropy kernel
        i = 0
        w = pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]
        if pw is not None:
            log_w = (pw - 1) * tf_ + 1
            out = (1 - tf_) * zf + log_w * (
                jnp.logaddexp(0.0, -jnp.abs(zf)) + jnp.maximum(-zf, 0.0))
        else:
            out = jnp.maximum(zf, 0.0) - zf * tf_ + \
                jnp.logaddexp(0.0, -jnp.abs(zf))
        if w is not None:
            out = out * w
        return _reduce(out, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply(fn, *args, name="bce_with_logits")


def mse_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.square(a - b), reduction),
                 input, label, name="mse_loss")


def square_error_cost(input, label):
    return apply(lambda a, b: jnp.square(a - b), input, label,
                 name="square_error_cost")


def l1_loss(input, label, reduction="mean", name=None):
    return apply(lambda a, b: _reduce(jnp.abs(a - b), reduction),
                 input, label, name="l1_loss")


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def fn(logp, t, *rest):
        t = t.astype(jnp.int32)
        safe = jnp.where(t == ignore_index, 0, t)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(safe, 1), axis=1).squeeze(1)
        mask = (t != ignore_index)
        if rest:
            wl = jnp.take(rest[0], safe) * mask
        else:
            wl = mask.astype(logp.dtype)
        loss = -picked * wl
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(wl), 1e-12)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply(fn, *args, name="nll_loss")


def kl_div(input, label, reduction="mean", name=None):
    def fn(logp, t):
        out = t * (jnp.log(jnp.clip(t, 1e-12)) - logp)
        if reduction == "batchmean":
            return jnp.sum(out) / logp.shape[0]
        return _reduce(out, reduction)
    return apply(fn, input, label, name="kl_div")


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = jnp.abs(a - b)
        out = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta)
        return _reduce(out, reduction)
    return apply(fn, input, label, name="smooth_l1_loss")


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def fn(a, b, t):
        out = jnp.maximum(0.0, -t * (a - b) + margin)
        return _reduce(out, reduction)
    return apply(fn, input, other, label, name="margin_ranking_loss")


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean",
                         name=None):
    def fn(a, t):
        out = jnp.where(t == 1.0, a, jnp.maximum(0.0, margin - a))
        return _reduce(out, reduction)
    return apply(fn, input, label, name="hinge_embedding_loss")


def cosine_embedding_loss(input1, input2, label, margin=0.0,
                          reduction="mean", name=None):
    def fn(a, b, t):
        cos = jnp.sum(a * b, -1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        out = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(out, reduction)
    return apply(fn, input1, input2, label, name="cosine_embedding_loss")


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, t):
        return -t * jnp.log(p + epsilon) - (1 - t) * jnp.log(1 - p + epsilon)
    return apply(fn, input, label, name="log_loss")


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, t, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0.0) - z * t + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            out = out / rest[0]
        return _reduce(out, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply(fn, *args, name="sigmoid_focal_loss")


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def fn(a, pos, neg):
        dp = jnp.power(jnp.sum(jnp.abs(a - pos) ** p, -1) + epsilon, 1 / p)
        dn = jnp.power(jnp.sum(jnp.abs(a - neg) ** p, -1) + epsilon, 1 / p)
        if swap:
            dpn = jnp.power(jnp.sum(jnp.abs(pos - neg) ** p, -1) + epsilon,
                            1 / p)
            dn = jnp.minimum(dn, dpn)
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)
    return apply(fn, input, positive, negative, name="triplet_margin_loss")


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC loss (reference warpctc_op.cc) via a lax.scan forward algorithm —
    the TPU-native replacement for the warp-ctc CUDA library."""

    def fn(lp, lab, in_len, lab_len):
        # lp: [T, N, C] log-probs (paddle warpctc layout)
        T, N, C = lp.shape
        S = lab.shape[1]
        lab = lab.astype(jnp.int32)
        # extended label with blanks: length 2S+1
        ext = jnp.full((N, 2 * S + 1), blank, dtype=jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        ext_len = 2 * lab_len.astype(jnp.int32) + 1

        neg_inf = -1e30
        # alpha[0]
        a0 = jnp.full((N, 2 * S + 1), neg_inf)
        a0 = a0.at[:, 0].set(lp[0][:, blank])
        first_lab = jnp.take_along_axis(lp[0], ext[:, 1:2], axis=1)[:, 0]
        a0 = a0.at[:, 1].set(jnp.where(S > 0, first_lab, neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((N, 2), bool),
             ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            shift1 = jnp.concatenate(
                [jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
            shift2 = jnp.concatenate(
                [jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
            shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
            emit = jnp.take_along_axis(lp_t, ext, axis=1)
            return merged + emit, None

        def scan_body(carry, x):
            t, alpha = carry
            new_alpha, _ = step(alpha, x)
            alpha = jnp.where(t < 1, alpha, new_alpha)  # t=0 already done
            return (t + 1, alpha), alpha

        (_, _), alphas = jax.lax.scan(scan_body, (0, a0), lp)
        # pick alpha at t = input_length-1 for each batch element
        t_idx = jnp.clip(in_len.astype(jnp.int32) - 1, 0, T - 1)
        final = alphas[t_idx, jnp.arange(N)]  # [N, 2S+1]
        lastpos = jnp.clip(ext_len - 1, 0, 2 * S)
        l1 = jnp.take_along_axis(final, lastpos[:, None], axis=1)[:, 0]
        l2 = jnp.take_along_axis(
            final, jnp.maximum(lastpos - 1, 0)[:, None], axis=1)[:, 0]
        ll = jnp.logaddexp(l1, l2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lab_len.astype(loss.dtype), 1))
        return _reduce(loss, reduction)

    return apply(fn, log_probs, labels, input_lengths, label_lengths,
                 name="ctc_loss")


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference fluid/layers/nn.py dice_loss: 1 - 2|X∩Y| / (|X|+|Y|)
    over the per-example flattened probabilities."""
    def fn(x, y):
        y = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32),
                           x.shape[-1], dtype=x.dtype) \
            if y.shape != x.shape else y.astype(x.dtype)
        reduce_dims = tuple(range(1, x.ndim))
        inter = jnp.sum(x * y, axis=reduce_dims)
        union = jnp.sum(x, axis=reduce_dims) + jnp.sum(y, axis=reduce_dims)
        return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))

    return apply(fn, input, label, name="dice_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference fluid/layers/loss.py npair_loss (Sohn'16): softmax
    cross-entropy over anchor·positiveᵀ similarities + L2 on embeddings."""
    def fn(a, p, lab):
        sim = a @ p.T                                       # [B, B]
        same = (lab.reshape(-1, 1) == lab.reshape(1, -1)).astype(a.dtype)
        tgt = same / jnp.maximum(same.sum(axis=1, keepdims=True), 1)
        logp = jax.nn.log_softmax(sim, axis=1)
        ce = -(tgt * logp).sum(axis=1).mean()
        reg = l2_reg * (jnp.sum(a * a) + jnp.sum(p * p)) / \
            (2.0 * a.shape[0])
        return ce + reg

    return apply(fn, anchor, positive, labels, name="npair_loss")


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid loss (reference hierarchical_sigmoid_op.cc,
    MatrixBitCodeFunctor with SimpleCodeTable in
    operators/math/matrix_bit_code.h).

    Default tree = the reference's SimpleCode complete binary tree over
    `num_classes` leaves: for class c the heap code is c + num_classes;
    internal node for bit j is (code >> (j+1)) - 1 and the target bit is
    (code >> j) & 1. A custom tree comes in as (path_table, path_code)
    [N, L] padded with -1. Returns [N, 1] per-sample losses.

    is_sparse selects the reference's SelectedRows gradient for the
    weight table; on TPU the row gather below already yields a sparse
    (gather-transpose) gradient under XLA, so it is accepted and ignored.
    """
    args = [input, label, weight]
    has_bias = bias is not None
    if has_bias:
        args.append(bias)
    custom = path_table is not None
    if custom:
        args += [path_table, path_code]

    max_len = max((2 * num_classes - 1).bit_length() - 1, 1) \
        if not custom else None

    def fn(x, lab, w, *rest):
        b = rest[0] if has_bias else None
        lab = lab.reshape(-1).astype(jnp.int32)
        if custom:
            tbl = rest[-2].astype(jnp.int32)
            code = rest[-1].astype(jnp.int32)
            valid = (tbl >= 0).astype(jnp.float32)
            idx = jnp.maximum(tbl, 0)                      # [N, L]
            bits = code.astype(jnp.float32)
        else:
            c = lab + num_classes                          # [N]
            js = jnp.arange(max_len, dtype=jnp.int32)      # [L]
            idx = (c[:, None] >> (js[None, :] + 1)) - 1    # [N, L]
            bits = ((c[:, None] >> js[None, :]) & 1).astype(jnp.float32)
            valid = (idx >= 0).astype(jnp.float32)
            idx = jnp.maximum(idx, 0)
        rows = w[idx]                                      # [N, L, F]
        s = jnp.einsum("nf,nlf->nl", x.astype(jnp.float32),
                       rows.astype(jnp.float32))
        if b is not None:
            s = s + b.reshape(-1)[idx].astype(jnp.float32)
        # BCE-with-logits toward the code bit, masked to the real path
        per_bit = jax.nn.softplus(s) - bits * s
        return jnp.sum(per_bit * valid, axis=1, keepdims=True)

    return apply(fn, *args, name="hsigmoid_loss")
