"""Pooling functionals over lax.reduce_window.

Reference parity: /root/reference/paddle/fluid/operators/pool_op.cc,
pool_op.cu (cuDNN pooling) and python/paddle/nn/functional/pooling.py.
lax.reduce_window is the direct XLA lowering; adaptive pooling computes
per-bin windows statically (shapes are static under jit anyway).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.autograd import apply

__all__ = [
    "avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
    "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
    "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
    "adaptive_max_pool3d",
]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else v * n))[:n]
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = []
        for p in padding:
            if isinstance(p, (list, tuple)):
                flat.extend(int(x) for x in p)
            else:
                flat.append(int(p))
        if len(flat) == n:
            return [(p, p) for p in flat]
        return [(flat[2 * i], flat[2 * i + 1]) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _pool(x, kernel, stride, padding, n, data_format, reducer, init,
          ceil_mode=False, count_include_pad=True, divisor_override=None,
          name="pool"):
    channel_last = not data_format.startswith("NC")
    k = _tuplize(kernel, n)
    s = _tuplize(stride if stride is not None else kernel, n)
    p = _pads(padding, n)

    def fn(a):
        if channel_last:
            dims = (1,) + k + (1,)
            strides = (1,) + s + (1,)
        else:
            dims = (1, 1) + k
            strides = (1, 1) + s
        if isinstance(p, str):
            padcfg = p
        else:
            sp = [(0, 0), (0, 0)] if not channel_last else [(0, 0)]
            padcfg = sp + list(p) + ([] if not channel_last else [(0, 0)])
            if ceil_mode:
                # extend high padding so the last partial window is kept
                spatial = a.shape[2:] if not channel_last else a.shape[1:-1]
                padcfg = [list(q) for q in padcfg]
                off = 2 if not channel_last else 1
                for i in range(n):
                    size = spatial[i] + padcfg[off + i][0] + padcfg[off + i][1]
                    rem = (size - k[i]) % s[i]
                    if rem != 0:
                        padcfg[off + i][1] += s[i] - rem
                padcfg = [tuple(q) for q in padcfg]
        if reducer == "max":
            out = jax.lax.reduce_window(
                a, -jnp.inf if jnp.issubdtype(a.dtype, jnp.floating)
                else jnp.iinfo(a.dtype).min,
                jax.lax.max, dims, strides, padcfg)
        else:
            summed = jax.lax.reduce_window(
                a, 0.0, jax.lax.add, dims, strides, padcfg)
            if divisor_override:
                out = summed / divisor_override
            elif count_include_pad or isinstance(padcfg, str):
                out = summed / np.prod(k)
            else:
                ones = jnp.ones_like(a)
                counts = jax.lax.reduce_window(
                    ones, 0.0, jax.lax.add, dims, strides, padcfg)
                out = summed / counts
        return out.astype(a.dtype)

    return apply(fn, x, name=name)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "max", None,
                 ceil_mode=ceil_mode, name="max_pool1d")


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "max",
                 None, ceil_mode=ceil_mode, name="max_pool2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "max",
                 None, ceil_mode=ceil_mode, name="max_pool3d")


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _pool(x, kernel_size, stride, padding, 1, fmt, "avg", 0.0,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", 0.0,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 divisor_override=divisor_override, name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", 0.0,
                 ceil_mode=ceil_mode, count_include_pad=not exclusive,
                 divisor_override=divisor_override, name="avg_pool3d")


def _adaptive(x, output_size, n, data_format, reducer, name):
    channel_last = not data_format.startswith("NC")
    out_sizes = output_size if isinstance(output_size, (list, tuple)) else \
        (output_size,) * n
    out_sizes = tuple(int(v) if v is not None else None for v in out_sizes)

    def fn(a):
        spatial = a.shape[1:-1] if channel_last else a.shape[2:]
        targets = tuple(o if o is not None else s
                        for o, s in zip(out_sizes, spatial))
        out = a
        # Pool one spatial axis at a time: split into bins when divisible
        # (the common case — one reshape+mean, XLA-friendly), else gather
        # per-bin slices.
        for i in range(n):
            ax = (1 + i) if channel_last else (2 + i)
            size = out.shape[ax]
            tgt = targets[i]
            if size % tgt == 0:
                k = size // tgt
                new_shape = out.shape[:ax] + (tgt, k) + out.shape[ax + 1:]
                r = out.reshape(new_shape)
                out = (jnp.max(r, axis=ax + 1) if reducer == "max"
                       else jnp.mean(r, axis=ax + 1))
            else:
                starts = [(j * size) // tgt for j in range(tgt)]
                ends = [-(-((j + 1) * size) // tgt) for j in range(tgt)]
                pieces = []
                for s0, e0 in zip(starts, ends):
                    sl = [slice(None)] * out.ndim
                    sl[ax] = slice(s0, e0)
                    piece = out[tuple(sl)]
                    pieces.append(jnp.max(piece, axis=ax, keepdims=True)
                                  if reducer == "max"
                                  else jnp.mean(piece, axis=ax, keepdims=True))
                out = jnp.concatenate(pieces, axis=ax)
        return out.astype(a.dtype)

    return apply(fn, x, name=name)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "NCW", "avg", "adaptive_avg_pool1d")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, data_format, "avg",
                     "adaptive_avg_pool2d")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, data_format, "avg",
                     "adaptive_avg_pool3d")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 1, "NCW", "max", "adaptive_max_pool1d")


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 2, "NCHW", "max", "adaptive_max_pool2d")


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    return _adaptive(x, output_size, 3, "NCDHW", "max", "adaptive_max_pool3d")
