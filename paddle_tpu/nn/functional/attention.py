"""Attention functionals.

The reference's attention is a chain of separate ops (matmul → scale →
softmax → dropout → matmul; fused only in inference via
fused/multihead_matmul_op.cu). Here the training path gets a real fused
kernel: on TPU, `flash_attention` lowers to a Pallas blockwise-softmax
kernel (paddle_tpu.ops.flash_attention) that never materializes the
[B,H,S,S] score matrix in HBM; elsewhere it falls back to the XLA
composite, which XLA still fuses reasonably.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.autograd import apply

__all__ = ["scaled_dot_product_attention", "flash_attention"]


def _sdpa_reference(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
                    scale=None, key=None):
    """[B, S, H, D] layout (paddle convention for flash_attention)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qh = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal, scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity;
    inputs [B, S, H, D]."""
    from ...core import random as prandom

    rng = prandom.next_key() if (dropout_p > 0.0 and training) else None
    p = dropout_p if training else 0.0

    def fn(q, k, v, *rest):
        m = rest[0] if rest else None
        return _sdpa_reference(q, k, v, m, p, is_causal, scale, rng)

    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    return apply(fn, *args, name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True, kv_mask=None,
                    name=None):
    """Flash-attention entry point; uses the Pallas TPU kernel when
    available (paddle_tpu.ops.flash_attention: fused fwd+bwd, native GQA
    — k/v may carry fewer heads), XLA composite otherwise. kv_mask [B,S]
    (1 = attend) covers padded-batch pretraining without an O(S^2) bias."""
    from ... import ops as _ops

    if (_ops.flash_attention_available() and dropout == 0.0
            and not return_softmax):
        def fn(q, k, v, *rest):
            m = rest[0] if rest else None
            return _ops.flash_attention(q, k, v, causal=causal, kv_mask=m)
        args = [query, key, value]
        if kv_mask is not None:
            args.append(kv_mask)
        out = apply(fn, *args, name="flash_attention")
        return (out, None) if return_softmax else out

    # composite fallback: expand GQA heads (the kernel handles them
    # natively; the composite needs full-head k/v)
    h = (query.shape[2] if hasattr(query, "shape") else None)
    hkv = (key.shape[2] if hasattr(key, "shape") else None)
    if h is not None and hkv is not None and h != hkv:
        from ...tensor.manipulation import repeat_interleave
        key = repeat_interleave(key, h // hkv, axis=2)
        value = repeat_interleave(value, h // hkv, axis=2)
    mask_bias = None
    if kv_mask is not None:
        arr = kv_mask.data if hasattr(kv_mask, "data") else kv_mask
        mask_bias = jnp.where(arr[:, None, None, :] > 0, 0.0, -1e30) \
            .astype(jnp.float32)
    out = scaled_dot_product_attention(
        query, key, value, attn_mask=mask_bias, dropout_p=dropout,
        is_causal=causal, training=training)
    return (out, None) if return_softmax else out
