"""Attention functionals.

The reference's attention is a chain of separate ops (matmul → scale →
softmax → dropout → matmul; fused only in inference via
fused/multihead_matmul_op.cu). Here the training path gets a real fused
kernel: on TPU, `flash_attention` lowers to a Pallas blockwise-softmax
kernel (paddle_tpu.ops.flash_attention) that never materializes the
[B,H,S,S] score matrix in HBM; elsewhere it falls back to the XLA
composite, which XLA still fuses reasonably.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.autograd import apply

__all__ = ["scaled_dot_product_attention", "flash_attention"]


def _sdpa_reference(q, k, v, mask=None, dropout_p=0.0, is_causal=False,
                    scale=None, key=None):
    """[B, S, H, D] layout (paddle convention for flash_attention)."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qh = jnp.swapaxes(q, 1, 2)  # [B,H,S,D]
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) * s
    if is_causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(causal, scores, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            scores = jnp.where(mask, scores, -1e30)
        else:
            scores = scores + mask
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and key is not None:
        keep = jax.random.bernoulli(key, 1.0 - dropout_p, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout_p), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, scale=None, name=None):
    """paddle.nn.functional.scaled_dot_product_attention parity;
    inputs [B, S, H, D]."""
    from ...core import random as prandom

    rng = prandom.next_key() if (dropout_p > 0.0 and training) else None
    p = dropout_p if training else 0.0

    def fn(q, k, v, *rest):
        m = rest[0] if rest else None
        return _sdpa_reference(q, k, v, m, p, is_causal, scale, rng)

    args = [query, key, value]
    if attn_mask is not None:
        args.append(attn_mask)
    return apply(fn, *args, name="scaled_dot_product_attention")


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True, name=None):
    """Flash-attention entry point; uses the Pallas TPU kernel when
    available (paddle_tpu.ops.flash_attention), XLA composite otherwise."""
    from ... import ops as _ops

    if (_ops.flash_attention_available() and dropout == 0.0
            and not return_softmax):
        def fn(q, k, v):
            return _ops.flash_attention(q, k, v, causal=causal)
        out = apply(fn, query, key, value, name="flash_attention")
        return (out, None) if return_softmax else out

    out = scaled_dot_product_attention(query, key, value, dropout_p=dropout,
                                       is_causal=causal, training=training)
    return (out, None) if return_softmax else out
