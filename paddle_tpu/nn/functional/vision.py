"""Vision sampling/warping functionals.

Reference: paddle/fluid/operators/grid_sampler_op.h (bilinear grid
sampling with zero padding), affine_grid_op.h (theta -> sampling grid),
temporal_shift_op.h (TSM channel shifting).
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.autograd import apply

__all__ = ["grid_sample", "affine_grid", "temporal_shift"]


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Bilinear sampling of x [N, C, H, W] at grid [N, Hg, Wg, 2]
    (normalized coords in [-1, 1], (x, y) order — grid_sampler_op.h)."""
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"grid_sample mode must be bilinear/nearest, "
                         f"got {mode!r}")
    if padding_mode not in ("zeros", "border"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")

    def fn(xa, ga):
        n, c, h, w = xa.shape
        gx, gy = ga[..., 0], ga[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        bidx = jnp.arange(n)[:, None, None]

        def take(ix, iy):
            inside = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            ixc = jnp.clip(ix, 0, w - 1)
            iyc = jnp.clip(iy, 0, h - 1)
            v = xa[bidx, :, iyc, ixc]                # [N, Hg, Wg, C]
            if padding_mode == "zeros":
                v = jnp.where(inside[..., None], v, 0.0)
            return v

        if mode == "nearest":
            out = take(jnp.round(fx).astype(jnp.int32),
                       jnp.round(fy).astype(jnp.int32))
            return jnp.moveaxis(out, -1, 1)

        x0 = jnp.floor(fx).astype(jnp.int32)
        y0 = jnp.floor(fy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = (fx - x0).astype(xa.dtype)[..., None]
        wy = (fy - y0).astype(xa.dtype)[..., None]
        out = (take(x0, y0) * (1 - wx) * (1 - wy) +
               take(x1, y0) * wx * (1 - wy) +
               take(x0, y1) * (1 - wx) * wy +
               take(x1, y1) * wx * wy)
        return jnp.moveaxis(out, -1, 1)              # [N, C, Hg, Wg]

    return apply(fn, x, grid, name="grid_sample")


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """theta [N, 2, 3] -> sampling grid [N, H, W, 2] (affine_grid_op)."""
    n, _, h, w = [int(s) for s in out_shape]

    def fn(th):
        if align_corners:
            xs = jnp.linspace(-1.0, 1.0, w)
            ys = jnp.linspace(-1.0, 1.0, h)
        else:
            xs = (jnp.arange(w) * 2 + 1) / w - 1
            ys = (jnp.arange(h) * 2 + 1) / h - 1
        gx, gy = jnp.meshgrid(xs, ys)                # [H, W]
        base = jnp.stack([gx, gy, jnp.ones_like(gx)], -1)  # [H, W, 3]
        return jnp.einsum("hwk,nck->nhwc", base.astype(th.dtype), th)

    return apply(fn, theta, name="affine_grid")


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    """TSM shift (temporal_shift_op.h): x [N*T, C, H, W]; the first
    shift_ratio channels shift -1 in time, the next shift_ratio shift
    +1, the rest stay."""
    def fn(xa):
        nt, c, h, w = xa.shape
        t = seg_num
        n = nt // t
        v = xa.reshape(n, t, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        pad = jnp.zeros_like(v[:, :1])
        fwd = jnp.concatenate([v[:, 1:, :c1], pad[:, :, :c1]], axis=1)
        back = jnp.concatenate([pad[:, :, c1:c2], v[:, :-1, c1:c2]],
                               axis=1)
        keep = v[:, :, c2:]
        return jnp.concatenate([fwd, back, keep],
                               axis=2).reshape(nt, c, h, w)

    return apply(fn, x, name="temporal_shift")
