"""Convolution functionals over jax.lax.conv_general_dilated.

Reference parity: /root/reference/paddle/fluid/operators/conv_op.cc,
conv_transpose_op.cc and python/paddle/nn/functional/conv.py. The
reference dispatches to cuDNN algorithms; here XLA tiles convs straight
onto the MXU (conv = matmul over im2col internally), so one lax primitive
covers every variant (stride/dilation/groups/transpose) with no algorithm
search. Weight layout follows paddle: [out_c, in_c/groups, *spatial].
"""
from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp

from ...core.autograd import apply

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _tuplize(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(x) for x in v)
    return tuple(int(v) for _ in range(n))


def _padding_arg(padding, n):
    """paddle padding: int, list of n ints, list of 2n ints (pairs), 'SAME',
    'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = []
        for p in padding:
            if isinstance(p, (list, tuple)):
                flat.extend(int(x) for x in p)
            else:
                flat.append(int(p))
        if len(flat) == n:
            return [(p, p) for p in flat]
        if len(flat) == 2 * n:
            # Could be [[0,0],[0,0],[ph,ph],[pw,pw]] NCHW-style or pairs.
            return [(flat[2 * i], flat[2 * i + 1]) for i in range(n)]
        raise ValueError(f"bad padding {padding}")
    return [(int(padding), int(padding))] * n


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else \
            ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else \
        ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n,
          data_format, name):
    channel_last = not data_format.startswith("NC")
    st = _tuplize(stride, n)
    dl = _tuplize(dilation, n)
    pad = _padding_arg(padding, n)
    dn = _dim_numbers(n, channel_last)

    def fn(a, w, *rest):
        # paddle weights are [O, I/g, *spatial]; lax wants layout per dn.
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = w.transpose(perm)
        out = jax.lax.conv_general_dilated(
            a, w, window_strides=st, padding=pad, rhs_dilation=dl,
            dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=a.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(fn, x, weight, bias, name=name)
    return apply(fn, x, weight, name=name)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 fmt, "conv1d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 data_format, "conv2d")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 data_format, "conv3d")


def _conv_transpose(x, weight, bias, stride, padding, output_padding,
                    dilation, groups, n, data_format, output_size, name):
    channel_last = not data_format.startswith("NC")
    st = _tuplize(stride, n)
    dl = _tuplize(dilation, n)
    opad = _tuplize(output_padding, n) if output_padding is not None else \
        (0,) * n
    pad = _padding_arg(padding, n)
    dn = _dim_numbers(n, channel_last)

    def fn(a, w, *rest):
        # paddle transpose-conv weights: [in_c, out_c/g, *spatial].
        # Use conv_general_dilated with lhs_dilation (fractional stride) —
        # the gradient-of-conv formulation XLA lowers natively.
        if isinstance(pad, str):
            if pad == "SAME":
                pads = []
                for i in range(n):
                    k = (w.shape[2 + i] - 1) * dl[i] + 1
                    total = max(k - st[i], 0)
                    pads.append((total // 2, total - total // 2))
            else:
                pads = [(0, 0)] * n
        else:
            pads = pad
        # transposed conv padding: k-1-p on each side, plus output_padding
        # on the high side.
        tpads = []
        for i in range(n):
            k = (w.shape[2 + i] - 1) * dl[i] + 1
            lo = k - 1 - pads[i][0]
            hi = k - 1 - pads[i][1] + opad[i]
            tpads.append((lo, hi))
        # weight [I, O/g, *s] -> flip spatial, swap I/O per group
        wf = jnp.flip(w, axis=tuple(range(2, 2 + n)))
        if groups > 1:
            i_c, og, *sp = wf.shape
            wf = wf.reshape(groups, i_c // groups, og, *sp)
            wf = jnp.swapaxes(wf, 1, 2)
            wf = wf.reshape(groups * og, i_c // groups, *sp)
        else:
            wf = jnp.swapaxes(wf, 0, 1)
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            wf = wf.transpose(perm)
        out = jax.lax.conv_general_dilated(
            a, wf, window_strides=(1,) * n, padding=tpads,
            lhs_dilation=st, rhs_dilation=dl, dimension_numbers=dn,
            feature_group_count=groups, preferred_element_type=a.dtype)
        if output_size is not None:
            target = _tuplize(output_size, n)
            slices = [slice(None)] * out.ndim
            off = 1 if channel_last else 2
            for i in range(n):
                slices[off + i] = slice(0, target[i])
            out = out[tuple(slices)]
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out

    if bias is not None:
        return apply(fn, x, weight, bias, name=name)
    return apply(fn, x, weight, name=name)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    fmt = "NCW" if data_format in ("NCL", "NCW") else "NWC"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, fmt, output_size,
                           "conv1d_transpose")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size,
                           "conv2d_transpose")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size,
                           "conv3d_transpose")
