"""Common functionals: linear, dropout, embedding, padding, one_hot, ...

Reference parity: python/paddle/nn/functional/common.py and the C++ ops
mul_op/matmul_v2_op (linear), dropout_op.cc, lookup_table_v2_op.cc
(embedding), pad3d_op.cc, one_hot_v2_op.cc, interpolate_v2 ops.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as prandom
from ...core.autograd import apply
from ...core.dtype import convert_dtype
from ...core.tensor import Tensor

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "pad", "zeropad2d", "unfold", "fold",
    "interpolate", "upsample", "cosine_similarity", "pixel_shuffle",
    "pixel_unshuffle", "label_smooth", "bilinear", "channel_shuffle",
]


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W shaped [in, out] (reference
    nn/functional/common.py linear → matmul_v2 + elementwise_add; MXU path:
    one jnp.dot, XLA fuses the bias add)."""
    if bias is None:
        return apply(lambda a, w: jnp.matmul(a, w), x, weight, name="linear")
    return apply(lambda a, w, b: jnp.matmul(a, w) + b, x, weight, bias,
                 name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    """reference dropout_op.cc; upscale_in_train is the default (inverted
    dropout). axis allows broadcast masks (feature dropout)."""
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply(lambda a: a * (1.0 - p), x, name="dropout")
        return apply(lambda a: a, x, name="dropout")
    if p == 1.0:
        return apply(lambda a: jnp.zeros_like(a), x, name="dropout")
    key = prandom.next_key()

    def fn(a):
        shape = list(a.shape)
        if axis is not None:
            axes = [axis] if isinstance(axis, int) else list(axis)
            shape = [s if i in axes else 1 for i, s in enumerate(a.shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return jnp.where(keep, a, 0.0).astype(a.dtype)

    return apply(fn, x, name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return apply(lambda a: a, x, name="alpha_dropout")
    key = prandom.next_key()
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale

    def fn(a):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        q = 1.0 - p
        a_coef = (q + alpha_p ** 2 * q * p) ** -0.5
        b_coef = -a_coef * alpha_p * p
        return (a_coef * jnp.where(keep, a, alpha_p) + b_coef).astype(a.dtype)

    return apply(fn, x, name="alpha_dropout")


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """reference lookup_table_v2_op.cc. With sparse=True the EAGER weight
    gradient is a SelectedRows (rows + values) instead of a dense
    [vocab, dim] table — the reference's is_sparse path.  Inside traced/
    compiled steps the op is the plain gather either way (XLA fuses the
    dense scatter-add fine; sparsity is a host-side update optimization)."""
    if sparse:
        from ...core.selected_rows import embedding_sparse
        return embedding_sparse(x, weight, padding_idx)

    def fn(ids, w):
        out = jnp.take(w, ids.astype(jnp.int32), axis=0)
        if padding_idx is not None:
            mask = (ids == padding_idx)[..., None]
            out = jnp.where(mask, 0.0, out)
        return out

    return apply(fn, x, weight, name="embedding")


def one_hot(x, num_classes, name=None):
    return apply(lambda a: jax.nn.one_hot(a, num_classes), x, name="one_hot")


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """reference pad3d_op.cc / nn/functional/common.py pad. Single
    implementation lives in tensor.manipulation.pad."""
    from ...tensor.manipulation import pad as _pad
    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference unfold_op.cc / math/im2col.cc). Returns
    [N, C*kh*kw, L]."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else (
        kernel_sizes, kernel_sizes)
    st = strides if isinstance(strides, (list, tuple)) else (strides, strides)
    pd = paddings if isinstance(paddings, (list, tuple)) else (
        paddings, paddings, paddings, paddings)
    if len(pd) == 2:
        pd = (pd[0], pd[0], pd[1], pd[1])
    dl = dilations if isinstance(dilations, (list, tuple)) else (
        dilations, dilations)

    def fn(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, ((0, 0), (0, 0), (pd[0], pd[1]), (pd[2], pd[3])))
        patches = jax.lax.conv_general_dilated_patches(
            a, filter_shape=ks, window_strides=st, padding="VALID",
            rhs_dilation=dl, dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(n, patches.shape[1], -1)

    return apply(fn, x, name="unfold")


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im: inverse of unfold (reference fold_op.cc)."""
    os = output_sizes if isinstance(output_sizes, (list, tuple)) else (
        output_sizes, output_sizes)
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else (
        kernel_sizes, kernel_sizes)
    st = strides if isinstance(strides, (list, tuple)) else (strides, strides)
    pd = paddings if isinstance(paddings, (list, tuple)) else (
        paddings, paddings)
    dl = dilations if isinstance(dilations, (list, tuple)) else (
        dilations, dilations)

    def fn(a):
        n, ckk, L = a.shape
        c = ckk // (ks[0] * ks[1])
        oh = (os[0] + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
        ow = (os[1] + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
        cols = a.reshape(n, c, ks[0], ks[1], oh, ow)
        out = jnp.zeros((n, c, os[0] + 2 * pd[0], os[1] + 2 * pd[1]), a.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                hi = i * dl[0]
                wj = j * dl[1]
                out = out.at[:, :, hi:hi + oh * st[0]:st[0],
                             wj:wj + ow * st[1]:st[1]].add(cols[:, :, i, j])
        return out[:, :, pd[0]:pd[0] + os[0], pd[1]:pd[1] + os[1]]

    return apply(fn, x, name="fold")


def _align_corners_interp_axis(a, axis, out_size):
    """Linear interpolation along one axis with the align_corners grid:
    x_in = x_out * (in-1)/(out-1) (reference interpolate_v2 align_corners
    branch)."""
    in_size = a.shape[axis]
    if out_size == in_size:
        return a
    if out_size == 1 or in_size == 1:
        idx = jnp.zeros((out_size,), jnp.int32)
        return jnp.take(a, idx, axis=axis)
    coords = jnp.arange(out_size, dtype=jnp.float32) * \
        ((in_size - 1) / (out_size - 1))
    lo = jnp.floor(coords).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, in_size - 1)
    w = coords - lo.astype(jnp.float32)
    shape = [1] * a.ndim
    shape[axis] = out_size
    w = w.reshape(shape)
    return (jnp.take(a, lo, axis=axis) * (1 - w) +
            jnp.take(a, hi, axis=axis) * w)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    """reference interpolate_v2 ops (nearest/bilinear/bicubic/trilinear/
    linear/area). Half-pixel sampling via jax.image.resize; the
    align_corners grid (x_in = x_out*(in-1)/(out-1)) is computed as
    separable per-axis linear gathers for linear/bilinear/trilinear."""
    mode = mode.lower()
    if isinstance(size, Tensor):
        size = [int(v) for v in np.asarray(size.data)]

    def fn(a):
        channel_last = not data_format.startswith("NC")
        spatial = a.shape[1:-1] if channel_last else a.shape[2:]
        if size is not None:
            out_spatial = tuple(int(s) for s in (
                size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial)
            out_spatial = tuple(int(s * f) for s, f in zip(spatial, sf))
        axes = (tuple(range(1, a.ndim - 1)) if channel_last
                else tuple(range(2, a.ndim)))
        if align_corners and mode in ("linear", "bilinear", "trilinear"):
            out = a.astype(jnp.float32)
            for ax, t in zip(axes, out_spatial):
                out = _align_corners_interp_axis(out, ax, t)
            return out.astype(a.dtype)
        if channel_last:
            full = (a.shape[0],) + out_spatial + (a.shape[-1],)
        else:
            full = a.shape[:2] + out_spatial
        method = {"nearest": "nearest", "bilinear": "bilinear",
                  "bicubic": "bicubic", "trilinear": "trilinear",
                  "linear": "linear", "area": "linear"}[mode]
        if method == "trilinear":
            method = "linear"
        return jax.image.resize(a, full, method=method).astype(a.dtype)

    return apply(fn, x, name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners,
                       align_mode, data_format)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply(fn, x1, x2, name="cosine_similarity")


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c // (r * r), r, r, h, w)
            a = a.transpose(0, 1, 4, 2, 5, 3)
            return a.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, r, r, c // (r * r))
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h * r, w * r, c // (r * r))

    return apply(fn, x, name="pixel_shuffle")


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, c, h // r, r, w // r, r)
            a = a.transpose(0, 1, 3, 5, 2, 4)
            return a.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = a.shape
        a = a.reshape(n, h // r, r, w // r, r, c)
        a = a.transpose(0, 1, 3, 2, 4, 5)
        return a.reshape(n, h // r, w // r, c * r * r)

    return apply(fn, x, name="pixel_unshuffle")


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def fn(a):
        if data_format == "NCHW":
            n, c, h, w = a.shape
            a = a.reshape(n, groups, c // groups, h, w)
            return a.transpose(0, 2, 1, 3, 4).reshape(n, c, h, w)
        n, h, w, c = a.shape
        a = a.reshape(n, h, w, groups, c // groups)
        return a.transpose(0, 1, 2, 4, 3).reshape(n, h, w, c)
    return apply(fn, x, name="channel_shuffle")


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    """reference label_smooth_op.cc."""
    def fn(l, *rest):
        k = l.shape[-1]
        if rest:
            return (1 - epsilon) * l + epsilon * rest[0]
        return (1 - epsilon) * l + epsilon / k
    if prior_dist is not None:
        return apply(fn, label, prior_dist, name="label_smooth")
    return apply(fn, label, name="label_smooth")


def bilinear(x1, x2, weight, bias=None, name=None):
    """reference bilinear_tensor_product_op.cc: out[n,o] =
    x1[n,i] W[o,i,j] x2[n,j] + b[o]."""
    def fn(a, b, w, *rest):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    if bias is not None:
        return apply(fn, x1, x2, weight, bias, name="bilinear")
    return apply(fn, x1, x2, weight, name="bilinear")
