"""paddle.nn.functional parity surface (reference
python/paddle/nn/functional/__init__.py)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention)
from . import extension  # noqa: F401
from .extension import diag_embed, gather_tree  # noqa: F401
