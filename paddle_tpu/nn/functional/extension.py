"""paddle.nn.functional.extension parity (reference
python/paddle/nn/functional/extension.py — diag_embed; gather_tree is
exported beside it from fluid.layers in the reference __init__)."""
from ...tensor.creation import diag_embed  # noqa: F401
from ...text.decoding import gather_tree  # noqa: F401

__all__ = ["diag_embed", "gather_tree"]
