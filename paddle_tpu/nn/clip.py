"""Gradient clipping (reference python/paddle/fluid/clip.py:
ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm). Clippers operate
on (param, grad) pairs like the reference's _dygraph_clip, and also expose
a pure-array form (`clip_arrays`) for the compiled/pjit training path
where grads are a pytree of jax.Arrays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm", "clip_by_global_norm_arrays"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)

    def clip_arrays(self, grads):
        """Pure functional form over a pytree of arrays (jit-safe)."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.data, self.min, self.max))))
        return out

    def clip_arrays(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_one(self, g):
        norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
        return (g * scale).astype(g.dtype)

    def _dygraph_clip(self, params_grads):
        return [(p, g if g is None or not getattr(p, "need_clip", True)
                 else Tensor(self._clip_one(g.data)))
                for p, g in params_grads]

    def clip_arrays(self, grads):
        return jax.tree_util.tree_map(self._clip_one, grads)


def clip_by_global_norm_arrays(grads, clip_norm):
    """Global-norm clip over a pytree of arrays; returns (clipped, norm)."""
    leaves = jax.tree_util.tree_leaves(grads)
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                      for g in leaves))
    scale = jnp.minimum(clip_norm / jnp.maximum(gn, 1e-12), 1.0)
    return jax.tree_util.tree_map(
        lambda g: (g * scale).astype(g.dtype), grads), gn


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _dygraph_clip(self, params_grads):
        arrs = [g.data for p, g in params_grads
                if g is not None and getattr(p, "need_clip", True)]
        if not arrs:
            return params_grads
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(a.astype(jnp.float32)))
                          for a in arrs))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g.data * scale).astype(g.data.dtype))))
        return out

    def clip_arrays(self, grads):
        clipped, _ = clip_by_global_norm_arrays(grads, self.clip_norm)
        return clipped
