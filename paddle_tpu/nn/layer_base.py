"""Layer: the module base class.

TPU-native re-design of the reference dygraph Layer
(/root/reference/python/paddle/fluid/dygraph/layers.py — parameters,
sublayers, buffers, hooks, state_dict, train/eval) without the Scope/
Variable machinery: parameters are Parameter tensors held directly, and a
functional bridge (`functional_state` / `functional_call` in
paddle_tpu.func) turns any Layer into a pure fn over a param pytree so it
can be jit/grad/shard_map'ed — the equivalent of the reference's
dygraph-to-static ProgramTranslator path, but via tracing instead of AST
rewriting.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..core.dtype import convert_dtype, default_float_dtype
from ..core.tensor import Parameter, Tensor
from . import initializer as I

__all__ = ["Layer", "ParamAttr"]

# per-prefix counters: linear_0, layer_norm_0, linear_1 — reference
# unique_name semantics, not one global sequence across all classes
_layer_name_counters: Dict[str, int] = {}
# namespace prefix set by paddle_tpu.utils.unique_name.guard("ns_")
_layer_name_prefix: str = ""


class ParamAttr:
    """Parameter attribute bag (reference python/paddle/fluid/param_attr.py:
    name/initializer/learning_rate/regularizer/trainable)."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if attr is False:
            return False
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        raise TypeError(f"Cannot make ParamAttr from {type(attr)}")


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    """Base class for all network layers (reference
    fluid/dygraph/layers.py:Layer)."""

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        self.training = True
        self._dtype = convert_dtype(dtype) or default_float_dtype()
        if name_scope is None:
            # paddle-style unique scope (linear_0, linear_1, ...) so
            # default param names are linear_0.w_0 / linear_0.b_0
            prefix = _layer_name_prefix + self.__class__.__name__.lower()
            idx = _layer_name_counters.get(prefix, 0)
            _layer_name_counters[prefix] = idx + 1
            name_scope = f"{prefix}_{idx}"
        self._full_name = name_scope
        self._param_index = {"w": 0, "b": 0}
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0

    # ---- construction helpers --------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """reference layers.py Layer.create_parameter → LayerHelperBase."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = convert_dtype(dtype) or self._dtype
        init = attr.initializer or default_initializer
        if init is None:
            gw, gb = I.get_global_initializer()
            if is_bias:
                init = gb or I.Constant(0.0)
            else:
                init = gw or I.XavierUniform()
        data = init(shape, dtype)
        name = attr.name
        if name is None:
            # paddle-style default names (linear_0.w_0 / linear_0.b_0) so
            # name-based hooks (AdamW apply_decay_param_fun, Lamb
            # exclude_from_weight_decay_fn) can match bias/weight params
            kind = "b" if is_bias else "w"
            idx = self._param_index
            name = f"{self._full_name}.{kind}_{idx[kind]}"
            idx[kind] += 1
        p = Parameter(data, name=name, trainable=attr.trainable)
        p.optimize_attr = {"learning_rate": attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        p.is_distributed = False
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        return Tensor(jnp.zeros((), convert_dtype(dtype) or self._dtype),
                      name=name)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter or None")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: Optional["Layer"]):
        if sublayer is not None and not isinstance(sublayer, Layer):
            raise TypeError("add_sublayer expects a Layer or None")
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        elif name in self._non_persistable_buffer_names:
            self._non_persistable_buffer_names.remove(name)

    # ---- attribute protocol ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)  # un-shadow a prior plain attr
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            self.__dict__.pop(name, None)
            layers[name] = value
        elif params is not None and name in params:
            if value is not None:
                raise TypeError(f"cannot assign {type(value)} to parameter {name}")
            params[name] = value
        elif layers is not None and name in layers:
            if value is not None:
                raise TypeError(f"cannot assign {type(value)} to sublayer {name}")
            layers[name] = value
        elif buffers is not None and name in buffers:
            if value is not None and not isinstance(value, Tensor):
                value = Tensor(value)
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = []
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d:
                extra += list(d)
        return list(super().__dir__()) + extra

    # ---- traversal --------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True
                         ) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (name + "." + pname if name else pname), p

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True
                      ) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + "." + bname if name else bname), b

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = prefix + "." + name if prefix else name
            if id(layer) not in layers_set:
                yield sub_prefix, layer
                yield from layer.named_sublayers(
                    prefix=sub_prefix, include_self=False, layers_set=layers_set)

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ---- mode -------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    # ---- hooks ------------------------------------------------------------
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call -------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    # ---- state dict -------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix.rstrip("."), include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[(name + "." + bname) if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Load a state dict (reference layers.py Layer.set_state_dict).
        Returns (missing_keys, unexpected_keys)."""
        own = self.state_dict()
        missing, matched = [], set()
        for key, target in own.items():
            if key not in state_dict:
                missing.append(key)
                continue
            value = state_dict[key]
            arr = value.data if isinstance(value, Tensor) else jnp.asarray(
                np.asarray(value))
            if tuple(arr.shape) != tuple(target.data.shape):
                raise ValueError(
                    f"shape mismatch for {key}: loaded {tuple(arr.shape)} vs "
                    f"param {tuple(target.data.shape)}")
            target.set_value(arr)
            matched.add(key)
        unexpected = [k for k in state_dict if k not in own]
        return missing, unexpected

    load_dict = set_state_dict

    # ---- dtype / device ---------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        d = convert_dtype(dtype)
        if d is not None:
            self._dtype = d
            for p in self.parameters():
                if jnp.issubdtype(p.data.dtype, jnp.floating):
                    p.set_value(p.data.astype(d))
            for b in self.buffers():
                if b is not None and jnp.issubdtype(b.data.dtype, jnp.floating):
                    b.set_value(b.data.astype(d))
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def full_name(self):
        return self._full_name

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self.named_children():
            mod_str = repr(layer)
            mod_str = "\n  ".join(mod_str.split("\n"))
            lines.append(f"({name}): {mod_str}")
        main = self.__class__.__name__ + "("
        if extra and not lines:
            return main + extra + ")"
        if lines:
            return main + (extra + "\n  " if extra else "\n  ") + \
                "\n  ".join(lines) + "\n)"
        return main + ")"
