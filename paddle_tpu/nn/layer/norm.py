"""Normalization layers (reference python/paddle/nn/layer/norm.py;
kernels batch_norm_op.cc, layer_norm_op.cc, group_norm_op.cc,
instance_norm_op.cc; SyncBatchNorm = sync_batch_norm_op.cu whose NCCL
stat-allreduce becomes a psum over the data-parallel mesh axis)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Tensor
from .. import functional as F
from .. import initializer as I
from ..layer_base import Layer

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
           "InstanceNorm3D", "SyncBatchNorm", "LocalResponseNorm",
           "SpectralNorm", "RMSNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm-compatible alias."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        fmt = "NCW" if data_format in ("NC", "NCL") else "NWC"
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, fmt, use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. In the compiled data-parallel path the
    batch statistics are psum'ed over the 'dp' mesh axis automatically when
    running under shard_map (see paddle_tpu.distributed.sync_batch_norm);
    eagerly it behaves like BatchNorm (single process = single replica).
    Reference: sync_batch_norm_op.cu + python SyncBatchNorm.convert_sync_batchnorm."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(
                layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum,
                                layer._epsilon,
                                data_format=layer._data_format)
            out.weight = layer.weight
            out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            if sub is not None:
                out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight,
                            self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """RMS norm — not in the reference snapshot but required by modern LLM
    configs; kept API-compatible with paddle 2.6's RMSNorm."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None,
                 name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            self._normalized_shape, attr=weight_attr,
            default_initializer=I.Constant(1.0))

    def forward(self, x):
        from ...core.autograd import apply
        import jax

        eps = self._epsilon
        n = len(self._normalized_shape)

        def fn(a, w):
            axes = tuple(range(a.ndim - n, a.ndim))
            af = a.astype(jnp.float32)
            ms = jnp.mean(af * af, axis=axes, keepdims=True)
            return (af * jax.lax.rsqrt(ms + eps) * w).astype(a.dtype)

        return apply(fn, x, self.weight, name="rms_norm")


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is False or bias_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon,
                               data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, "NCW")


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr,
                         bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """reference spectral_norm_op.cc: power-iteration weight normalization."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= s
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0.0, 1.0))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0.0, 1.0))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.autograd import apply
        dim, iters, eps = self._dim, self._power_iters, self._eps

        # Power iteration runs outside the tape with persisted u/v (the
        # reference keeps U/V as persistable vars updated per step and
        # treats them as constants in the gradient).
        w_const = weight.data if isinstance(weight, Tensor) else weight
        wm_const = jnp.moveaxis(w_const, dim, 0).reshape(
            w_const.shape[dim], -1)
        u = self.weight_u.data
        v = self.weight_v.data
        for _ in range(iters):
            v = wm_const.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm_const @ v
            u = u / (jnp.linalg.norm(u) + eps)
        self.weight_u._data = u
        self.weight_v._data = v

        def fn(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            sigma = u @ wm @ v
            return w / sigma

        return apply(fn, weight, name="spectral_norm")
