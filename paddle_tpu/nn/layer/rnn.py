"""Recurrent layers: SimpleRNN / LSTM / GRU over lax.scan.

Reference parity: python/paddle/nn/layer/rnn.py (RNNCellBase, LSTMCell,
GRUCell, RNN, LSTM, GRU) whose compute is the cudnn_lstm / rnn_op C++
kernels. TPU-native design: the time loop is a jax.lax.scan (one compiled
loop, weights stay resident in VMEM across steps) instead of cuDNN's
fused descriptor API; gate matmuls are batched into a single [4H] / [3H]
projection per step to keep the MXU busy.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...core import random as prandom
from ...core.autograd import apply
from ...core.tensor import Tensor
from .. import initializer as I
from ..layer_base import Layer

__all__ = ["RNNCellBase", "SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "SimpleRNN",
           "LSTM", "GRU", "BiRNN"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        if shape is None:
            shape = (self.hidden_size,)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value,
                               self._dtype))

    @property
    def state_shape(self):
        return (self.hidden_size,)


def _std_uniform(hidden_size):
    k = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-k, k)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else \
            (lambda a: jnp.maximum(a, 0))

        def fn(x, h, wi, wh, bi, bh):
            return act(x @ wi.T + bi + h @ wh.T + bh)

        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="simple_rnn_cell")
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [4 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [4 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [4 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [4 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            h = self.get_initial_states(inputs)
            c = self.get_initial_states(inputs)
        else:
            h, c = states

        def fn(x, h_, c_, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h_ @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * c_ + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new

        h_new, c_new = apply(fn, inputs, h, c, self.weight_ih,
                             self.weight_hh, self.bias_ih, self.bias_hh,
                             name="lstm_cell")
        return h_new, (h_new, c_new)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        init = _std_uniform(hidden_size)
        self.weight_ih = self.create_parameter(
            [3 * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [3 * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [3 * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [3 * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xn = jnp.split(xg, 3, axis=-1)
            hr, hz, hn = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            return (1 - z) * n + z * h

        h = apply(fn, inputs, states, self.weight_ih, self.weight_hh,
                  self.bias_ih, self.bias_hh, name="gru_cell")
        return h, h


class RNN(Layer):
    """Wraps a cell into a full sequence loop (reference nn/layer/rnn.py
    RNN; C++ recurrent_op.cc). Uses lax.scan when the cell is one of the
    built-ins (fast path), python loop otherwise (custom cells)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        steps_axis = 0 if self.time_major else 1
        n = inputs.shape[steps_axis]
        outputs = []
        states = initial_states
        idx = range(n - 1, -1, -1) if self.is_reverse else range(n)
        for t in idx:
            x_t = inputs[t] if self.time_major else inputs[:, t]
            out, states = self.cell(x_t, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        from ...tensor.manipulation import stack
        return stack(outputs, axis=steps_axis), states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        s_fw, s_bw = (initial_states if initial_states is not None
                      else (None, None))
        out_fw, st_fw = self.rnn_fw(inputs, s_fw)
        out_bw, st_bw = self.rnn_bw(inputs, s_bw)
        from ...tensor.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) RNN over a fused lax.scan.

    The whole time loop for all layers compiles to nested scans — the
    TPU replacement for cudnn_lstm's fused multi-layer descriptor.
    """

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        gate_mult = {"LSTM": 4, "GRU": 3}.get(self.MODE, 1)
        init = _std_uniform(hidden_size)

        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                sfx = f"_l{layer}" + ("_reverse" if d else "")
                wih = self.create_parameter(
                    [gate_mult * hidden_size, in_sz], attr=weight_ih_attr,
                    default_initializer=init)
                whh = self.create_parameter(
                    [gate_mult * hidden_size, hidden_size],
                    attr=weight_hh_attr, default_initializer=init)
                bih = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_ih_attr,
                    is_bias=True, default_initializer=init)
                bhh = self.create_parameter(
                    [gate_mult * hidden_size], attr=bias_hh_attr,
                    is_bias=True, default_initializer=init)
                self.add_parameter(f"weight_ih{sfx}", wih)
                self.add_parameter(f"weight_hh{sfx}", whh)
                self.add_parameter(f"bias_ih{sfx}", bih)
                self.add_parameter(f"bias_hh{sfx}", bhh)
                self._all_weights.append((wih, whh, bih, bhh))

    def _cell_step(self, mode):
        if mode == "LSTM":
            def step(x, state, wi, wh, bi, bh):
                h_, c_ = state
                gates = x @ wi.T + bi + h_ @ wh.T + bh
                i, f, g, o = jnp.split(gates, 4, axis=-1)
                c_new = jax.nn.sigmoid(f) * c_ + \
                    jax.nn.sigmoid(i) * jnp.tanh(g)
                h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
                return h_new, (h_new, c_new)
        elif mode == "GRU":
            def step(x, state, wi, wh, bi, bh):
                h = state
                xr, xz, xn = jnp.split(x @ wi.T + bi, 3, axis=-1)
                hr, hz, hn = jnp.split(h @ wh.T + bh, 3, axis=-1)
                r = jax.nn.sigmoid(xr + hr)
                z = jax.nn.sigmoid(xz + hz)
                n = jnp.tanh(xn + r * hn)
                h_new = (1 - z) * n + z * h
                return h_new, h_new
        else:
            act = jnp.tanh if "TANH" in mode else (lambda a: jnp.maximum(a, 0))

            def step(x, state, wi, wh, bi, bh):
                h_new = act(x @ wi.T + bi + state @ wh.T + bh)
                return h_new, h_new
        return step

    def forward(self, inputs, initial_states=None, sequence_length=None):
        mode = self.MODE
        is_lstm = mode == "LSTM"
        nd = self.num_directions
        nl = self.num_layers
        hs = self.hidden_size
        time_major = self.time_major
        step = self._cell_step(mode)
        p_drop = self.dropout if self.training else 0.0
        drop_keys = ([prandom.next_key() for _ in range(nl - 1)]
                     if p_drop > 0.0 and nl > 1 else None)
        has_init = initial_states is not None

        def fn(x, *rest):
            if has_init:
                if is_lstm:
                    h_init, c_init = rest[0], rest[1]
                    flat_w = rest[2:]
                else:
                    h_init = rest[0]
                    c_init = None
                    flat_w = rest[1:]
            else:
                h_init = c_init = None
                flat_w = rest
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # -> [T, B, ...]
            batch = x.shape[1]
            ws = [flat_w[i * 4:(i + 1) * 4]
                  for i in range(nl * nd)]
            h_last, c_last = [], []
            layer_in = x
            for layer in range(nl):
                outs = []
                for d in range(nd):
                    i_state = layer * nd + d
                    wi, wh, bi, bh = ws[i_state]
                    if h_init is not None:
                        h0 = h_init[i_state].astype(x.dtype)
                        c0 = c_init[i_state].astype(x.dtype) if is_lstm \
                            else None
                    else:
                        h0 = jnp.zeros((batch, hs), x.dtype)
                        c0 = h0
                    state0 = (h0, c0) if is_lstm else h0
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    def scan_fn(state, x_t, wi=wi, wh=wh, bi=bi, bh=bh):
                        out, new_state = step(x_t, state, wi, wh, bi, bh)
                        return new_state, out

                    final, out_seq = jax.lax.scan(scan_fn, state0, seq)
                    if d == 1:
                        out_seq = jnp.flip(out_seq, 0)
                    outs.append(out_seq)
                    if is_lstm:
                        h_last.append(final[0])
                        c_last.append(final[1])
                    else:
                        h_last.append(final)
                layer_in = outs[0] if nd == 1 else \
                    jnp.concatenate(outs, axis=-1)
                if drop_keys is not None and layer < nl - 1:
                    keep = jax.random.bernoulli(
                        drop_keys[layer], 1.0 - p_drop, layer_in.shape)
                    layer_in = jnp.where(
                        keep, layer_in / (1.0 - p_drop), 0.0
                    ).astype(layer_in.dtype)
            y = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_last, 0)
            if is_lstm:
                return y, h_stack, jnp.stack(c_last, 0)
            return y, h_stack

        flat_weights = [w for group in self._all_weights for w in group]
        args = [inputs]
        if has_init:
            if is_lstm:
                args += [initial_states[0], initial_states[1]]
            else:
                args.append(initial_states)
        out = apply(fn, *args, *flat_weights, name=mode.lower())
        if is_lstm:
            y, h, c = out
            return y, (h, c)
        y, h = out
        return y, h


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        self.MODE = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(input_size, hidden_size, num_layers, direction,
                         time_major, dropout, **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
