"""paddle.nn.PairwiseDistance (reference nn/layer/distance.py — the
p-norm of x-y along the last axis via dist/p_norm kernels)."""
from __future__ import annotations

import jax.numpy as jnp

from ...core.autograd import apply
from ..layer_base import Layer

__all__ = ["PairwiseDistance"]


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = float(p)
        self.epsilon = float(epsilon)
        self.keepdim = keepdim

    def forward(self, x, y):
        p, eps, keep = self.p, self.epsilon, self.keepdim

        def fn(a, b):
            d = (a - b).astype(jnp.float32) + eps
            if p == float("inf"):
                return jnp.max(jnp.abs(d), axis=-1, keepdims=keep)
            return jnp.sum(jnp.abs(d) ** p, axis=-1,
                           keepdims=keep) ** (1.0 / p)

        return apply(fn, x, y, name="pairwise_distance")
