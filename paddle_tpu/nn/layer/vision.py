"""paddle.nn.vision namespace (reference python/paddle/nn/layer/vision.py
— PixelShuffle; the upsampling layers live beside it in common.py here)."""
from .common import (PixelShuffle, PixelUnshuffle, ChannelShuffle,  # noqa: F401
                     Upsample, UpsamplingBilinear2D, UpsamplingNearest2D)

__all__ = ["PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
           "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D"]
