"""Parameter initializers.

TPU-native re-design of the reference initializer suite
(/root/reference/python/paddle/fluid/initializer.py — ConstantInitializer,
UniformInitializer, NormalInitializer, TruncatedNormal, Xavier, MSRA
(Kaiming), NumpyArrayInitializer). The reference appends fill ops into a
startup Program executed once; here an Initializer is a callable
`(shape, dtype, key) -> jax.Array` evaluated eagerly at Layer construction
(there is no separate startup program — XLA has no use for one).
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as prandom
from ..core.dtype import convert_dtype

__all__ = [
    "Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
    "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
    "Assign", "Bilinear", "calculate_gain", "set_global_initializer",
]

_global_weight_init = None
_global_bias_init = None


class Initializer:
    """Base initializer (reference fluid/initializer.py:Initializer)."""

    def __call__(self, shape: Sequence[int], dtype=None, key=None):
        raise NotImplementedError

    def _key(self, key):
        return key if key is not None else prandom.next_key()

    @staticmethod
    def _fans(shape):
        """Receptive-field-aware fan computation (reference
        initializer.py Initializer._compute_fans)."""
        shape = tuple(shape)
        if len(shape) == 0:
            return 1, 1
        if len(shape) == 1:
            return shape[0], shape[0]
        if len(shape) == 2:
            return shape[0], shape[1]
        # conv kernels: [out_c, in_c, *spatial] (paddle layout)
        receptive = int(np.prod(shape[2:]))
        return shape[1] * receptive, shape[0] * receptive


class Constant(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        return jnp.full(tuple(shape), self.value, convert_dtype(dtype) or jnp.float32)


class Normal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        d = convert_dtype(dtype) or jnp.float32
        return self.mean + self.std * jax.random.normal(
            self._key(key), tuple(shape), d)


class TruncatedNormal(Initializer):
    def __init__(self, mean: float = 0.0, std: float = 1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=None, key=None):
        d = convert_dtype(dtype) or jnp.float32
        return self.mean + self.std * jax.random.truncated_normal(
            self._key(key), -2.0, 2.0, tuple(shape), d)


class Uniform(Initializer):
    def __init__(self, low: float = -1.0, high: float = 1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=None, key=None):
        d = convert_dtype(dtype) or jnp.float32
        return jax.random.uniform(self._key(key), tuple(shape), d,
                                  self.low, self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = self._fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return Normal(0.0, std)(shape, dtype, key)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain: float = 1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=None, key=None):
        fi, fo = self._fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return Uniform(-limit, limit)(shape, dtype, key)


class KaimingNormal(Initializer):
    """MSRA init (reference initializer.py MSRAInitializer)."""

    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = self._fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return Normal(0.0, std)(shape, dtype, key)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope: float = 0.0,
                 nonlinearity: str = "relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=None, key=None):
        fi, _ = self._fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return Uniform(-limit, limit)(shape, dtype, key)


class Assign(Initializer):
    """Initialize from a given array/list (reference NumpyArrayInitializer)."""

    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=None, key=None):
        arr = np.asarray(
            self.value.numpy() if hasattr(self.value, "numpy") else self.value)
        d = convert_dtype(dtype)
        out = jnp.asarray(arr, dtype=d)
        if tuple(out.shape) != tuple(shape):
            raise ValueError(
                f"Assign initializer shape {out.shape} != param shape {tuple(shape)}")
        return out


class Bilinear(Initializer):
    """Bilinear upsampling kernel for transposed conv (reference
    initializer.py BilinearInitializer)."""

    def __call__(self, shape, dtype=None, key=None):
        shape = tuple(shape)
        if len(shape) != 4 or shape[2] != shape[3]:
            raise ValueError("Bilinear expects [C_out, C_in, K, K]")
        k = shape[3]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        w = np.zeros(shape, dtype=np.float32)
        rng = np.arange(k)
        filt = (1 - np.abs(rng / f - c))
        kern = filt[:, None] * filt[None, :]
        for i in range(shape[0]):
            w[i, i % shape[1]] = kern
        return jnp.asarray(w, dtype=convert_dtype(dtype) or jnp.float32)


def calculate_gain(nonlinearity: str, param: Optional[float] = None) -> float:
    """paddle.nn.initializer.calculate_gain parity."""
    recommended = {
        "sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
        "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
        "conv3d_transpose": 1.0, "tanh": 5.0 / 3, "relu": math.sqrt(2.0),
        "selu": 3.0 / 4,
    }
    if nonlinearity == "leaky_relu":
        slope = 0.01 if param in (None, 0.0) else float(param or 0.01)
        if param == 0.0:
            slope = 0.0
        return math.sqrt(2.0 / (1 + slope ** 2))
    if nonlinearity in recommended:
        return recommended[nonlinearity]
    raise ValueError(f"Unsupported nonlinearity: {nonlinearity}")


def set_global_initializer(weight_init, bias_init=None):
    """paddle.nn.initializer.set_global_initializer parity."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def get_global_initializer():
    return _global_weight_init, _global_bias_init
