"""Weight normalization (reference
python/paddle/nn/utils/weight_norm_hook.py:155,202 — weight_norm /
remove_weight_norm).

Reparameterizes layer.weight as g * v / ||v|| where the norm is taken
over every axis except `dim`. Implemented the reference's way: replace
the parameter with (weight_g, weight_v) and recompute `weight` in a
forward pre-hook so autograd flows into g and v.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.autograd import apply
from ...core.tensor import Parameter

__all__ = ["weight_norm", "remove_weight_norm"]


def _norm_except_dim(v, dim):
    def fn(a):
        if dim is None:
            return jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2))
        axes = tuple(i for i in range(a.ndim) if i != dim % a.ndim)
        return jnp.sqrt(jnp.sum(a.astype(jnp.float32) ** 2, axis=axes,
                                keepdims=True)).astype(a.dtype)
    return apply(fn, v, name="norm_except_dim")


def _compute_weight(g, v, dim):
    def fn(ga, va):
        if dim is None:
            n = jnp.sqrt(jnp.sum(va.astype(jnp.float32) ** 2))
            return (ga * va / n).astype(va.dtype)
        axes = tuple(i for i in range(va.ndim) if i != dim % va.ndim)
        n = jnp.sqrt(jnp.sum(va.astype(jnp.float32) ** 2, axis=axes,
                             keepdims=True))
        return (ga.astype(jnp.float32) * va.astype(jnp.float32) / n) \
            .astype(va.dtype)
    return apply(fn, g, v, name="weight_norm")


class _WeightNormHook:
    def __init__(self, name, dim):
        self.name = name
        self.dim = dim

    def __call__(self, layer, inputs):
        g = getattr(layer, self.name + "_g")
        v = getattr(layer, self.name + "_v")
        setattr(layer, self.name, _compute_weight(g, v, self.dim))
        return None


def weight_norm(layer, name="weight", dim=0):
    """Apply weight normalization to `layer.<name>`; returns the layer."""
    if hasattr(layer, "_weight_norm_hooks") and \
            name in layer._weight_norm_hooks:
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    if w is None:
        raise ValueError(f"{type(layer).__name__} has no parameter "
                         f"{name!r}")
    g0 = _norm_except_dim(w, dim)
    v0 = w
    del layer._parameters[name]
    layer.add_parameter(name + "_g", Parameter(g0.data))
    layer.add_parameter(name + "_v", Parameter(v0.data))
    hook = _WeightNormHook(name, dim)
    handle = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_weight_norm_hooks"):
        layer._weight_norm_hooks = {}
    layer._weight_norm_hooks[name] = (hook, handle)
    # keep a usable .weight between calls (eval-time access)
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    """Undo weight_norm: restore a single `name` parameter."""
    hooks = getattr(layer, "_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    hook, handle = hooks.pop(name)
    handle.remove()
    g = getattr(layer, name + "_g")
    v = getattr(layer, name + "_v")
    w = _compute_weight(g, v, hook.dim)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if hasattr(layer, name):
        try:
            delattr(layer, name)
        except AttributeError:
            pass
    layer.add_parameter(name, Parameter(w.data))
    return layer
