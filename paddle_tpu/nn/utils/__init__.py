"""paddle.nn.utils (reference python/paddle/nn/utils/__init__.py)."""
from . import weight_norm_hook  # noqa: F401
from .weight_norm_hook import weight_norm, remove_weight_norm  # noqa: F401

__all__ = ["weight_norm", "remove_weight_norm"]
