"""paddle.nn parity surface (reference python/paddle/nn/__init__.py).

Layer system over the eager tape / functional bridge; see layer_base.py.
"""
from .layer_base import Layer, ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)

functional_alias = functional
