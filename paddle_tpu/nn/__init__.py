"""paddle.nn parity surface (reference python/paddle/nn/__init__.py).

Layer system over the eager tape / functional bridge; see layer_base.py.
"""
from .layer_base import Layer, ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from .utils import weight_norm_hook  # noqa: F401
from .functional import extension  # noqa: F401
from .layer import *  # noqa: F401,F403
from .layer.common import *  # noqa: F401,F403
from .layer import vision  # noqa: F401
from .layer.distance import PairwiseDistance  # noqa: F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm)

functional_alias = functional
