"""paddle.nn.BeamSearchDecoder + dynamic_decode (reference
python/paddle/nn/decode.py over fluid/layers/rnn.py BeamSearchDecoder /
dynamic_decode).

The reference unrolls decoding with a While loop over LoDTensorArrays;
here the whole search is one compiled lax.scan (text/decoding.py
beam_search) — the TPU-native shape of the same API: the decoder bundles
cell + embedding + output projection, dynamic_decode runs it to
max_step_num and returns beam-sorted ids.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Beam-search decoding driver around an RNN cell.

    cell: an RNNCellBase (SimpleRNNCell/LSTMCell/GRUCell) or any callable
      (inputs [N, E], states) -> (outputs [N, H], new_states).
    embedding_fn: token ids [N] -> embeddings [N, E] (defaults to one-hot
      of vocab_size inferred from output_fn if omitted — pass it).
    output_fn: cell outputs [N, H] -> vocab logits [N, V].
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] (reference staticmethod of the same
        name): repeat each batch row beam_size times."""
        a = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(a, beam_size, axis=0)
        return Tensor(tiled) if isinstance(x, Tensor) else tiled

    def _step_fn(self):
        def step(tokens, state):
            if self.embedding_fn is not None:
                emb = self.embedding_fn(tokens)
            else:
                raise ValueError("BeamSearchDecoder needs embedding_fn")
            emb = emb.data if isinstance(emb, Tensor) else emb
            out, new_state = self._call_cell(emb, state)
            logits = out if self.output_fn is None else self.output_fn(out)
            logits = logits.data if isinstance(logits, Tensor) else logits
            return logits, new_state
        return step

    def _call_cell(self, inputs, states):
        res = self.cell(inputs, states)
        out, new_states = res
        out = out.data if isinstance(out, Tensor) else out
        new_states = jax.tree_util.tree_map(
            lambda t: t.data if isinstance(t, Tensor) else t, new_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num=100,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run the decoder to max_step_num steps (reference dynamic_decode).

    inits: initial cell state with leading batch dim B (it is tiled to
    B*beam internally). Returns (predicted_ids, scores) — ids
    [B, T, beam] (or [T, B, beam] when output_time_major), beam-sorted
    best first — plus per-beam lengths when return_length.
    """
    from ..text.decoding import beam_search

    if inits is None:
        raise ValueError("dynamic_decode needs the initial cell state")
    K = decoder.beam_size

    def prep(t):
        a = t.data if isinstance(t, Tensor) else jnp.asarray(t)
        return jnp.repeat(a, K, axis=0)

    state0 = jax.tree_util.tree_map(
        prep, inits, is_leaf=lambda t: isinstance(t, Tensor))
    leaves = jax.tree_util.tree_leaves(state0)
    B = leaves[0].shape[0] // K

    seqs, scores = beam_search(
        decoder._step_fn(), state0, batch_size=B, beam_size=K,
        max_len=int(max_step_num), bos_id=decoder.start_token,
        eos_id=decoder.end_token)
    ids = jnp.moveaxis(seqs.data, 1, 2)            # [B, T, K]
    if output_time_major:
        ids = jnp.moveaxis(ids, 0, 1)              # [T, B, K]
    out = (Tensor(ids), scores)
    if return_length:
        eos_hit = (seqs.data == decoder.end_token)
        T = seqs.data.shape[2]
        first = jnp.argmax(eos_hit.astype(jnp.int32), axis=2) + 1
        lengths = jnp.where(eos_hit.any(axis=2), first, T)
        return out + (Tensor(lengths.astype(jnp.int64)),)
    return out
