"""paddle.callbacks parity — re-export of hapi callbacks (reference
python/paddle/callbacks pointing at hapi/callbacks.py)."""
from .hapi.callbacks import (  # noqa: F401
    Callback, EarlyStopping, History, LRScheduler, ModelCheckpoint,
    ProgBarLogger)
