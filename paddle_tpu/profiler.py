"""Profiler — trace annotations, trace capture, per-step timing.

Reference: paddle/fluid/platform/profiler.h:127 (`RecordEvent` RAII
markers), :210 (`EnableProfiler`/`DisableProfiler` state machine),
device_tracer.h:43 (CUPTI kernel timeline -> chrome trace), python
fluid/profiler.py:131,198,255 (profiler ctx manager, start/stop).

TPU-native: XLA already timestamps every HLO on-device; what the
framework owns is (1) host-side trace annotations that show up nested
inside the device timeline (jax.profiler.TraceAnnotation ==
RecordEvent), (2) capture control writing TensorBoard/Perfetto traces
(start_trace/stop_trace == EnableProfiler -> chrome-trace file), and
(3) cheap per-step wall timing for training loops (hapi logs
`step_time_ms` through StepTimer) — the profiler.py summary-table role.
"""
from __future__ import annotations

import contextlib
import functools
import time
from typing import Optional

import jax

__all__ = ["RecordEvent", "record_event", "profiler", "start_profiler",
           "stop_profiler", "StepTimer", "memory_stats", "cost_stats"]

_active_trace_dir: Optional[str] = None


class RecordEvent:
    """Host-side trace annotation (reference platform/profiler.h:127).
    Context manager or decorator; nests inside the device trace when a
    capture is active, costs ~nothing when idle.

    Two sinks per event (ISSUE 13): the jax TraceAnnotation shows the
    span nested inside a device capture, and — when the structured span
    tracer is armed (observability.spans) — the same enter/exit pair
    lands in the process span buffer for Chrome-trace export, so one
    RecordEvent instruments both the device timeline and the host
    timeline."""

    def __init__(self, name: str, args: Optional[dict] = None):
        self.name = name
        self.args = args
        self._ann = None
        self._t0_us = 0.0

    def __enter__(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        from .observability import spans as _spans
        tr = _spans.tracer()
        if tr.active:
            self._t0_us = tr.now_us()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        self._ann = None
        from .observability import spans as _spans
        tr = _spans.tracer()
        if tr.active:
            now = tr.now_us()
            tr.complete(self.name, self._t0_us, now - self._t0_us,
                        cat="record_event", args=self.args)
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapped(*a, **k):
            with RecordEvent(self.name):
                return fn(*a, **k)
        return wrapped


record_event = RecordEvent


def start_profiler(log_dir: str = "/tmp/paddle_tpu_profile",
                   tracer_option: Optional[str] = None):
    """reference fluid/profiler.py:198 start_profiler /
    platform EnableProfiler: begin a capture; artifacts are a
    TensorBoard/Perfetto trace under log_dir."""
    global _active_trace_dir
    if _active_trace_dir is not None:
        raise RuntimeError("profiler already started")
    jax.profiler.start_trace(log_dir)
    _active_trace_dir = log_dir
    return log_dir


def stop_profiler(sorted_key=None, profile_path: Optional[str] = None):
    """reference fluid/profiler.py:255 stop_profiler."""
    global _active_trace_dir
    if _active_trace_dir is None:
        return None
    jax.profiler.stop_trace()
    out, _active_trace_dir = _active_trace_dir, None
    return out


@contextlib.contextmanager
def profiler(log_dir: str = "/tmp/paddle_tpu_profile", state=None,
             tracer_option=None, profile_path=None):
    """reference fluid/profiler.py:131 profiler context manager."""
    start_profiler(log_dir, tracer_option)
    try:
        yield
    finally:
        stop_profiler(profile_path=profile_path)


class StepTimer:
    """Wall-clock step statistics (the summary-table half of the
    reference profiler). tick() after each step; read .last_ms /
    .mean_ms / .p50_ms."""

    def __init__(self, warmup: int = 1):
        self.warmup = warmup
        self.times_ms = []
        self._t0 = None
        self._seen = 0

    def start(self):
        self._t0 = time.perf_counter()

    def tick(self):
        now = time.perf_counter()
        if self._t0 is not None:
            self._seen += 1
            if self._seen > self.warmup:
                self.times_ms.append((now - self._t0) * 1e3)
        self._t0 = now

    @property
    def last_ms(self):
        return self.times_ms[-1] if self.times_ms else None

    @property
    def mean_ms(self):
        return sum(self.times_ms) / len(self.times_ms) \
            if self.times_ms else None

    @property
    def p50_ms(self):
        if not self.times_ms:
            return None
        s = sorted(self.times_ms)
        return s[len(s) // 2]

    def summary(self):
        return {"steps": len(self.times_ms), "mean_ms": self.mean_ms,
                "p50_ms": self.p50_ms, "last_ms": self.last_ms}


def _analysis_degraded(stage: str, exc=None) -> dict:
    """An executable whose XLA analysis is unavailable (jaxlib CPU
    deserialized executables return None or raise) degrades to {} —
    the exec registry keeps the entry timing-only — and the failure is
    counted so a fleet dashboard can see the blind spot."""
    try:
        from .observability import metrics as _metrics
        _metrics.counter(
            "exec_analysis_failures_total",
            "executable cost/memory analyses that degraded to "
            "timing-only", labels=("stage",)).labels(stage=stage).inc()
    except Exception:
        pass
    return {}


def memory_stats(compiled) -> dict:
    """Peak-memory evidence for a compiled executable (reference
    monitor.h STAT_ADD GPU-mem stats). Works on jax.jit(...).lower(...)
    .compile() results and SpmdTrainer.step_executable.  Backends where
    ``memory_analysis()`` returns None or raises (jaxlib CPU
    deserialized executables) yield {} instead of throwing, with an
    ``exec_analysis_failures_total`` count."""
    try:
        ma = compiled.memory_analysis()
    except Exception as e:
        return _analysis_degraded("memory_analysis", e)
    if ma is None:
        return _analysis_degraded("memory_analysis")
    try:
        return {
            "argument_bytes": ma.argument_size_in_bytes,
            "output_bytes": ma.output_size_in_bytes,
            "temp_bytes": ma.temp_size_in_bytes,
            "alias_bytes": ma.alias_size_in_bytes,
            "peak_bytes": ma.argument_size_in_bytes +
            ma.output_size_in_bytes + ma.temp_size_in_bytes -
            ma.alias_size_in_bytes,
        }
    except Exception as e:
        return _analysis_degraded("memory_analysis", e)


def cost_stats(compiled) -> dict:
    """FLOP/byte estimates from XLA's cost analysis.  Same degradation
    contract as memory_stats: None / raising backends yield {}."""
    try:
        ca = compiled.cost_analysis()
    except Exception as e:
        return _analysis_degraded("cost_analysis", e)
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict) or ca is None:
        return _analysis_degraded("cost_analysis")
    return {"flops": ca.get("flops", 0.0),
            "bytes_accessed": ca.get("bytes accessed", 0.0)}
