"""Blocked/fused softmax-cross-entropy over a linear vocabulary head.

The reference computes the LM loss as two separate ops — a [B*S, V]
logits matmul (mul_op) followed by softmax_with_cross_entropy_op — which
materializes the full logits tensor twice (fwd + grad). At GPT scale
that tensor dominates HBM traffic: b8/s2048/v50k in fp32 is ~3.3 GB per
direction per step, all of it read and written just to reduce to one
scalar per token.

This op fuses projection + logsumexp + gather into ONE pass over the
vocabulary in chunks: for each vocab block it computes the block's
logits from (hidden [N, H], weight [V, H]), folds them into a running
online max/denominator (the flash-attention trick applied to the vocab
axis), and picks out the label logit when it falls inside the block.
The full [N, V] logits tensor never exists — peak extra memory is one
[N, block] tile. The custom VJP recomputes each block's logits from the
saved per-row logsumexp in the backward pass (residuals are just
hidden, weight, labels, lse: O(N*H + V*H + N)), producing d(hidden) and
d(weight) chunkwise the same way.

Semantics match nn.functional.cross_entropy(soft_label=False,
use_softmax=True, reduction='none') exactly for fp32 inputs: per-row
loss = logsumexp(x @ W.T) - (x @ W.T)[label], 0.0 where
label == ignore_index. Matmuls run in the storage dtype with f32
accumulation (preferred_element_type), so bf16 inputs keep MXU rate.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["fused_linear_cross_entropy", "pick_vocab_block"]

_NEG = -1e30


def pick_vocab_block(vocab_size: int, want: int = 2048) -> int:
    """Largest power-of-two chunk <= want that is <= vocab_size (the
    vocab is padded up to a multiple of the chunk, so divisibility is
    not required — only that one chunk is not absurdly oversized)."""
    b = 1
    while b * 2 <= min(want, vocab_size):
        b *= 2
    return b


def _dot_nt(a, b):
    """a [n, h] @ b.T [h, v] with f32 accumulation, inputs kept in their
    storage dtype (bf16 matmul inputs run at full MXU rate)."""
    return jax.lax.dot_general(a, b, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


def _pad_vocab(weight, block):
    v = weight.shape[0]
    n_blocks = -(-v // block)
    vp = n_blocks * block
    if vp != v:
        weight = jnp.pad(weight, ((0, vp - v), (0, 0)))
    return weight, n_blocks


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _blocked_ce(hidden, weight, labels, block, ignore_index):
    loss, _ = _blocked_ce_fwd(hidden, weight, labels, block, ignore_index)
    return loss


def _blocked_ce_fwd(hidden, weight, labels, block, ignore_index):
    n = hidden.shape[0]
    v = weight.shape[0]
    labels = labels.astype(jnp.int32)
    wpad, n_blocks = _pad_vocab(weight, block)

    def body(c, carry):
        m, l, lab_logit = carry
        w_blk = jax.lax.dynamic_slice_in_dim(wpad, c * block, block, 0)
        logits = _dot_nt(hidden, w_blk)                    # [n, block] f32
        cols = c * block + jax.lax.broadcasted_iota(
            jnp.int32, (1, block), 1)
        logits = jnp.where(cols < v, logits, _NEG)
        m_new = jnp.maximum(m, jnp.max(logits, axis=1))
        l = l * jnp.exp(m - m_new) + \
            jnp.sum(jnp.exp(logits - m_new[:, None]), axis=1)
        off = labels - c * block
        in_blk = (off >= 0) & (off < block)
        picked = jnp.take_along_axis(
            logits, jnp.clip(off, 0, block - 1)[:, None], axis=1)[:, 0]
        lab_logit = jnp.where(in_blk, picked, lab_logit)
        return m_new, l, lab_logit

    m0 = jnp.full((n,), _NEG, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    g0 = jnp.zeros((n,), jnp.float32)
    m, l, lab_logit = jax.lax.fori_loop(0, n_blocks, body, (m0, l0, g0))
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    valid = labels != ignore_index
    loss = jnp.where(valid, lse - lab_logit, 0.0)
    return loss, (hidden, weight, labels, lse)


def _blocked_ce_bwd(block, ignore_index, res, g):
    hidden, weight, labels, lse = res
    v = weight.shape[0]
    wpad, n_blocks = _pad_vocab(weight, block)
    # rows with ignored labels contribute no gradient
    gv = (g * (labels != ignore_index)).astype(jnp.float32)    # [n]

    def body(dx, c):
        w_blk = jax.lax.dynamic_slice_in_dim(wpad, c * block, block, 0)
        logits = _dot_nt(hidden, w_blk)                    # [n, block] f32
        cols = c * block + jax.lax.broadcasted_iota(
            jnp.int32, (1, block), 1)
        logits = jnp.where(cols < v, logits, _NEG)
        p = jnp.exp(logits - lse[:, None])                 # softmax block
        off = labels - c * block
        onehot = (off[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (1, block), 1)).astype(jnp.float32)
        d_logits = (p - onehot) * gv[:, None]              # [n, block]
        dx = dx + jax.lax.dot_general(
            d_logits, w_blk.astype(jnp.float32),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [n, h]
        dw_blk = jax.lax.dot_general(
            d_logits, hidden.astype(jnp.float32),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [block, h]
        return dx, dw_blk

    dx0 = jnp.zeros(hidden.shape, jnp.float32)
    dx, dws = jax.lax.scan(body, dx0, jnp.arange(n_blocks))
    dw = dws.reshape(n_blocks * block, -1)[:v]
    # integer primal -> float0 cotangent (jax custom_vjp convention)
    import numpy as np
    dlab = np.zeros(labels.shape, jax.dtypes.float0)
    return dx.astype(hidden.dtype), dw.astype(weight.dtype), dlab


_blocked_ce.defvjp(_blocked_ce_fwd, _blocked_ce_bwd)


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index=-100,
                               reduction="mean", block_size=None):
    """Softmax cross-entropy of `hidden @ weight.T` against integer
    `labels`, computed blockwise over the vocab so the full [N, V]
    logits tensor is never materialized (fwd or bwd).

    hidden [N, H]; weight [V, H] (embedding layout — the tied LM head);
    labels [N] int. Rows with labels == ignore_index produce loss 0 and
    no gradient. reduction: 'none' | 'mean' | 'sum'; 'mean' divides by
    the count of non-ignored rows (min 1), matching
    nn.functional.cross_entropy.
    """
    labels = labels.astype(jnp.int32)
    if labels.ndim == 2 and labels.shape[-1] == 1:
        labels = labels[:, 0]
    block = block_size or pick_vocab_block(weight.shape[0])
    loss = _blocked_ce(hidden, weight, labels, block, ignore_index)
    if reduction == "none":
        return loss
    if reduction == "sum":
        return jnp.sum(loss)
    denom = jnp.maximum(
        jnp.sum((labels != ignore_index).astype(jnp.float32)), 1.0)
    return jnp.sum(loss) / denom
