"""Pallas TPU flash attention — fused forward AND backward.

The reference has no training-time fused attention (only the inference
fused/multihead_matmul_op.cu); this kernel is the TPU-native upgrade: the
[B,H,S,S] score matrix never leaves VMEM in either direction — forward
streams k/v blocks through the MXU with a running max/denominator
(online softmax), backward recomputes the probabilities blockwise from
the saved per-row logsumexp (the standard flash recompute strategy), so
HBM traffic is O(S·D) instead of O(S²) for fwd and bwd alike.

Backward = two kernels sharing the recompute:
  - dq: per q-block, loop over k-blocks; dq_i = scale * Σ_j ds_ij k_j
  - dk/dv: per k-block, loop over q-blocks (and GQA groups);
    dv_j = Σ_i p_ij do_i, dk_j = scale * Σ_i ds_ij q_i
  with p_ij = exp(scale·q_i·k_j − lse_i), ds_ij = p_ij (do_i·v_j − δ_i),
  δ_i = do_i·o_i (one cheap XLA rowsum before the kernels).
All inner [block_q, block_k] tiles live in registers/VMEM only.

GQA is native: q is laid out [B·Hkv, G, S, D] and k/v [B·Hkv, S, D]; the
grid walks (kv-head, group, block), so grouped-query models never
materialize repeat_interleaved K/V (G enters as a grid dimension, and
the dk/dv kernel accumulates over it in-place across grid steps).

An optional key-padding mask [B, S] (1 = attend, 0 = masked) covers the
padded-batch pretraining case without an O(S²) bias tensor; arbitrary
additive masks still fall back to the XLA composite.

Layout contract: q [B, S, H, D], k/v [B, S, Hkv, D] with H % Hkv == 0.
"""
from __future__ import annotations

import functools
import json
import math
import os
import time

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional on CPU-only hosts
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_INTERPRET = False  # set True in tests to run the kernel on CPU
_NEG = -1e30


def set_interpret_mode(flag: bool):
    global _INTERPRET
    _INTERPRET = bool(flag)


def flash_attention_available() -> bool:
    if not _HAS_PLTPU:
        return False
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref, *,
                block_k: int, causal: bool, scale: float):
    """One (bh, g, q_block) program. q_ref [bq,d]; k/v [S,d]; m_ref (1,S)
    key mask; outputs o [bq,d] and lse (1,bq)."""
    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    # keep q/k/v in their storage dtype (bf16) for the MXU dots — f32
    # matmul inputs run at a fraction of the bf16 MXU rate; accumulation
    # stays f32 via preferred_element_type (the standard mixed scheme)
    q = q_ref[:]
    qi = pl.program_id(2)
    q_start = qi * block_q

    m0 = jnp.full((block_q, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bk] f32
        # reshape the f32 mask BEFORE comparing: mosaic can't insert a
        # minor dim on 1-bit vectors
        kv_f = m_ref[0, pl.ds(j * block_k, block_k)]       # (bk,) f32
        sblk = jnp.where(kv_f[None, :] > 0, sblk, _NEG)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            sblk = jnp.where(rows >= cols, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)  # fully-masked rows
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        last = (q_start + block_q + block_k - 1) // block_k
        n_iter = min(last, n_k) if isinstance(last, int) \
            else jnp.minimum(last, n_k)
    else:
        n_iter = n_k
    m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0, :] = (m + jnp.log(jnp.maximum(l, 1e-30)))[:, 0]


# ---------------------------------------------------------------------------
# backward kernels (everything in [bk, bq] orientation: lse/delta live on
# the lane axis, so no sublane broadcasts or transposes are emitted)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, m_ref,
                   dq_ref, *, block_k: int, causal: bool, scale: float):
    """One (bh, g, q_block): dq for this q block."""
    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k
    qi = pl.program_id(2)
    q_start = qi * block_q

    # bf16 MXU inputs, f32 accumulation (see _fwd_kernel note)
    qs = q_ref[:]                                          # [bq, d]
    do = do_ref[:]                                         # [bq, d]
    lse = lse_ref[0, :]                                    # (bq,)
    delta = dl_ref[0, :]                                   # (bq,)

    def body(j, dq_acc):
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        st = jax.lax.dot_general(
            k_blk, qs, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bk, bq]
        kv_f = m_ref[0, pl.ds(j * block_k, block_k)]       # (bk,) f32
        st = jnp.where(kv_f[:, None] > 0, st, _NEG)
        if causal:
            krows = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            qcols = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            st = jnp.where(qcols >= krows, st, _NEG)
        pT = jnp.exp(st - lse[None, :])                    # [bk, bq]
        pT = jnp.where(st <= _NEG / 2, 0.0, pT)
        dpT = jax.lax.dot_general(
            v_blk, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, bq]
        dsT = (pT * (dpT - delta[None, :])).astype(k_blk.dtype)
        return dq_acc + jax.lax.dot_general(
            dsT, k_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, d]

    if causal:
        last = (q_start + block_q + block_k - 1) // block_k
        n_iter = min(last, n_k) if isinstance(last, int) \
            else jnp.minimum(last, n_k)
    else:
        n_iter = n_k
    dq = jax.lax.fori_loop(0, n_iter, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dl_ref, m_ref,
                    dk_ref, dv_ref, *, block_q: int, causal: bool,
                    scale: float, n_groups: int):
    """One (bh, k_block, g): dk/dv for this k block, accumulated over the
    GQA group grid dimension (g innermost; init at g == 0)."""
    block_k, d = k_ref.shape
    s = q_ref.shape[0]
    n_q = s // block_q
    kj = pl.program_id(1)
    g = pl.program_id(2)
    k_start = kj * block_k

    # bf16 MXU inputs, f32 accumulation (see _fwd_kernel note)
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    kv_f = m_ref[0, pl.ds(k_start, block_k)]               # (bk,) f32

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.ds(i * block_q, block_q), :]      # [bq, d]
        do_blk = do_ref[pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(i * block_q, block_q)]      # (bq,)
        delta = dl_ref[0, pl.ds(i * block_q, block_q)]
        st = jax.lax.dot_general(
            k_blk, q_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bk, bq]
        st = jnp.where(kv_f[:, None] > 0, st, _NEG)
        if causal:
            krows = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 0)
            qcols = i * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_k, block_q), 1)
            st = jnp.where(qcols >= krows, st, _NEG)
        pT = jnp.exp(st - lse[None, :])
        pT = jnp.where(st <= _NEG / 2, 0.0, pT)
        pT16 = pT.astype(do_blk.dtype)
        dv_acc = dv_acc + jax.lax.dot_general(
            pT16, do_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        dpT = jax.lax.dot_general(
            v_blk, do_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, bq]
        dsT = (pT * (dpT - delta[None, :])).astype(q_blk.dtype)
        dk_acc = dk_acc + jax.lax.dot_general(
            dsT, q_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bk, d]
        return dk_acc, dv_acc

    i0 = k_start // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        i0, n_q, body, (jnp.zeros((block_k, d), jnp.float32),
                        jnp.zeros((block_k, d), jnp.float32)))
    # dk_j = scale * Σ ds_ij q_i (scale was folded into q before the
    # bf16-input rework; now applied once here)
    dk = dk * scale

    @pl.when(g == 0)
    def _init():
        dk_ref[:] = dk.astype(dk_ref.dtype)
        dv_ref[:] = dv.astype(dv_ref.dtype)

    if n_groups > 1:
        @pl.when(g > 0)
        def _accum():
            dk_ref[:] = dk_ref[:] + dk.astype(dk_ref.dtype)
            dv_ref[:] = dv_ref[:] + dv.astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call wrappers over the GQA layout
#   q4 [BHkv, G, S, D], k3/v3 [BHkv, S, D], mask [B, 1, S]
# ---------------------------------------------------------------------------
def _pick_block(s, want=256):
    while s % want:
        want //= 2
    return want


# ---------------------------------------------------------------------------
# block-size autotuning
#
# The fixed (512, 512) tiles the kernel shipped with are a safe middle
# ground, not an optimum: the right tile trades VMEM footprint (the
# [block_q, block_k] f32 score tile + the full k/v strips) against grid
# overhead and MXU occupancy, and the balance shifts with sequence
# length and head_dim. The table below carries per-shape defaults from
# a one-shot fwd+bwd sweep on TPU v5 lite (bf16, GPT head shapes);
# unknown shapes fall back to the nearest tabled sequence and finally
# to the fixed defaults, and every choice is clamped by _pick_block so
# a bad entry can never produce an invalid grid.
#
# PADDLE_TPU_FLASH_AUTOTUNE: "1" (default) = table lookup,
# "0" = fixed defaults, "sweep" = run a one-shot on-device sweep for
# each new shape and cache it for the process (TPU only).
# ---------------------------------------------------------------------------
_DEFAULT_BLOCKS = (512, 512)

# (device_kind, seq, head_dim, causal) -> (block_q, block_k)
_AUTOTUNE_TABLE = {
    # v5 lite: 16 MB VMEM/core; d=64 leaves room for wide k blocks, and
    # causal masking favors taller q blocks (fewer skipped k iterations
    # per program)
    ("v5e", 1024, 64, True): (512, 512),
    ("v5e", 1024, 64, False): (512, 1024),
    ("v5e", 2048, 64, True): (512, 1024),
    ("v5e", 2048, 64, False): (512, 1024),
    ("v5e", 4096, 64, True): (1024, 1024),
    ("v5e", 4096, 64, False): (512, 1024),
    ("v5e", 8192, 64, True): (1024, 1024),
    # d=128 doubles every strip; halve the q tile to stay under budget
    ("v5e", 1024, 128, True): (256, 512),
    ("v5e", 2048, 128, True): (256, 512),
    ("v5e", 4096, 128, True): (512, 512),
    # v5p / v6e carry more VMEM bandwidth; same shapes, wider k
    ("v5p", 2048, 64, True): (512, 1024),
    ("v6e", 2048, 64, True): (512, 1024),
}

_SWEEP_CACHE: dict = {}
_SWEEP_CANDIDATES = (128, 256, 512, 1024)

# On-disk persistence of the sweep table: an on-device sweep costs tens
# of seconds of compile+measure per shape, so PADDLE_TPU_FLASH_AUTOTUNE=
# sweep pays once per (device_kind, seq, head_dim, causal) ACROSS
# processes, not once per run.  PADDLE_TPU_FLASH_AUTOTUNE_CACHE names the
# legacy JSON file ("0"/"off" disables persistence; default
# ~/.cache/paddle_tpu/flash_autotune.json).  Sweep winners ALSO land in
# the unified tuning table (utils.tuning, op "flash_blocks") — the
# generalization of this cache that serves quantized-matmul tiles, MoE
# a2a chunks and prefill buckets too; get_block_sizes consults it even
# outside sweep mode, so a tuned shape from any prior process wins over
# the built-in table.
_SWEEP_STORE_STATE = {"loaded": False}


def _sweep_store_path():
    p = os.environ.get("PADDLE_TPU_FLASH_AUTOTUNE_CACHE", "").strip()
    if p.lower() in ("0", "off", "false", "none"):
        return None
    if p:
        return os.path.expanduser(p)
    base = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(base, "paddle_tpu", "flash_autotune.json")


def _unified_table_enabled() -> bool:
    """Mirror flash winners into (and serve lookups from) the unified
    tuning table ONLY when the legacy env var is unset: an explicit
    PADDLE_TPU_FLASH_AUTOTUNE_CACHE pins flash entries to exactly that
    file (the documented pre-unification contract, and what keeps the
    legacy round-trip tests hermetic)."""
    return os.environ.get("PADDLE_TPU_FLASH_AUTOTUNE_CACHE") is None


def _sweep_key_str(key) -> str:
    kind, seq, d, causal = key
    return f"{kind}|{seq}|{d}|{int(causal)}"


def _load_sweep_store():
    """Merge the on-disk sweep tables into the process cache (once);
    entries this process already swept win over stale disk entries.
    Reads the legacy flash_autotune.json first (it predates the unified
    table, so existing deployments keep their winners), then the
    unified tuning table's "flash_blocks" entries."""
    if _SWEEP_STORE_STATE["loaded"]:
        return
    _SWEEP_STORE_STATE["loaded"] = True
    path = _sweep_store_path()
    if path:
        try:
            with open(path) as f:
                data = json.load(f)
            if isinstance(data, dict):
                for k, v in data.items():
                    parts = str(k).split("|")
                    if len(parts) != 4:
                        continue
                    key = (parts[0], int(parts[1]), int(parts[2]),
                           bool(int(parts[3])))
                    _SWEEP_CACHE.setdefault(key, (int(v[0]), int(v[1])))
        except (OSError, ValueError, TypeError, IndexError, KeyError):
            pass  # corrupt/unreadable table: sweep again, rewrite it
    if not _unified_table_enabled():
        return
    try:
        from ..utils import tuning as _tuning
        for parts, v in _tuning.entries("flash_blocks").items():
            if len(parts) != 4:
                continue
            key = (parts[0], int(parts[1]), int(parts[2]),
                   bool(int(parts[3])))
            _SWEEP_CACHE.setdefault(key, (int(v[0]), int(v[1])))
    except (ValueError, TypeError, IndexError, ImportError):
        pass


def _persist_sweep_entry(key, val):
    """Atomic read-modify-write of the sweep table via
    framework.fs.open_for_write (fsync before rename: a crash can never
    commit a truncated table that silently re-costs the sweep);
    best-effort.  Winners are mirrored into the unified tuning table so
    every tuning consumer shares one store going forward."""
    if _unified_table_enabled():
        try:
            from ..utils import tuning as _tuning
            _tuning.record("flash_blocks", key, list(val))
        except Exception:
            pass
    path = _sweep_store_path()
    if not path:
        return
    try:
        data = {}
        try:
            with open(path) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                data = loaded
        except (OSError, ValueError):
            pass
        data[_sweep_key_str(key)] = list(val)
        from ..framework.fs import open_for_write
        with open_for_write(path, "w") as f:
            json.dump(data, f, indent=0, sort_keys=True)
    except OSError:
        pass


def _normalize_kind(kind: str) -> str:
    from ..utils import tuning as _tuning
    return _tuning.normalize_kind(kind)


def _device_kind() -> str:
    from ..utils import tuning as _tuning
    return _tuning.device_kind()


def get_block_sizes(seq: int, head_dim: int, causal: bool,
                    device_kind: str | None = None):
    """(block_q, block_k) for this shape: sweep cache > env override >
    table (exact, then nearest tabled seq) > fixed defaults. Always
    clamped to divide seq."""
    kind = _normalize_kind(device_kind) if device_kind is not None \
        else _device_kind()
    key = (kind, seq, head_dim, bool(causal))
    mode = os.environ.get("PADDLE_TPU_FLASH_AUTOTUNE", "1")
    if mode == "0":
        bq, bk = _DEFAULT_BLOCKS
        return _pick_block(seq, bq), _pick_block(seq, bk)
    if key in _SWEEP_CACHE:
        return _SWEEP_CACHE[key]
    # unified tuning table (utils.tuning): a shape swept by ANY prior
    # process serves here without re-arming sweep mode
    if _unified_table_enabled():
        try:
            from ..utils import tuning as _tuning
            tuned = _tuning.lookup("flash_blocks", key)
            if tuned is not None:
                bq, bk = int(tuned[0]), int(tuned[1])
                return _pick_block(seq, bq), _pick_block(seq, bk)
        except (ValueError, TypeError, IndexError):
            pass
    # sweep only tunes THIS process's device: an explicit foreign
    # device_kind would re-run the sweep forever (the cache is keyed by
    # the local kind) and return tiles tuned for the wrong chip
    if (mode == "sweep" and kind == _device_kind()
            and kind.startswith(("v2", "v3", "v4", "v5", "v6"))):
        # a previous process may have paid for this sweep already
        _load_sweep_store()
        if key in _SWEEP_CACHE:
            return _SWEEP_CACHE[key]
        try:
            return autotune_sweep(seq, head_dim, causal)
        except Exception:  # sweep is best-effort; fall through to table
            pass
    if key in _AUTOTUNE_TABLE:
        bq, bk = _AUTOTUNE_TABLE[key]
        return _pick_block(seq, bq), _pick_block(seq, bk)
    # nearest tabled sequence for the same (kind, head_dim, causal) —
    # SWEPT entries (process cache / legacy file / unified tuning
    # table, all merged by _load_sweep_store) count alongside the
    # built-ins, so a sweep at seq 2048 serves seq 1920 too instead of
    # dropping to the fixed defaults; swept entries come first so they
    # win distance ties against the shipped table
    _load_sweep_store()
    near = [(s, v) for (k, s, d, c), v in _SWEEP_CACHE.items()
            if k == kind and d == head_dim and c == bool(causal)]
    near += [(s, v) for (k, s, d, c), v in _AUTOTUNE_TABLE.items()
             if k == kind and d == head_dim and c == bool(causal)]
    if near:
        _, (bq, bk) = min(near, key=lambda sv: abs(sv[0] - seq))
    else:
        bq, bk = _DEFAULT_BLOCKS
    return _pick_block(seq, bq), _pick_block(seq, bk)


def autotune_sweep(seq: int, head_dim: int, causal: bool, batch: int = 1,
                   heads: int = 4, iters: int = 5):
    """One-shot on-device sweep: time fwd+bwd for each candidate tile on
    a representative bf16 problem, cache the winner for the process.
    Called on TPU only (interpret-mode timings are meaningless)."""
    import numpy as np
    kind = _device_kind()
    key = (kind, seq, head_dim, bool(causal))
    rng = np.random.RandomState(0)
    q4 = jnp.asarray(rng.randn(batch * heads, 1, seq, head_dim)
                     .astype(np.float32) * 0.1, dtype=jnp.bfloat16)
    k3 = jnp.asarray(rng.randn(batch * heads, seq, head_dim)
                     .astype(np.float32) * 0.1, dtype=jnp.bfloat16)
    v3 = jnp.asarray(rng.randn(batch * heads, seq, head_dim)
                     .astype(np.float32) * 0.1, dtype=jnp.bfloat16)
    mask = jnp.ones((batch, 1, seq), jnp.float32)

    def step_time(bq, bk):
        fwd = jax.jit(functools.partial(
            _fwd_gqa, causal=causal, block_q=bq, block_k=bk))
        bwd = jax.jit(functools.partial(
            _bwd_gqa, causal=causal, block_q=bq, block_k=bk))
        o4, lse = fwd(q4, k3, v3, mask)
        outs = bwd(q4, k3, v3, mask, o4, lse, o4)
        jax.block_until_ready(outs)
        t0 = time.perf_counter()
        for _ in range(iters):
            o4, lse = fwd(q4, k3, v3, mask)
            outs = bwd(q4, k3, v3, mask, o4, lse, o4)
        jax.block_until_ready(outs)
        return (time.perf_counter() - t0) / iters

    best, best_t = _DEFAULT_BLOCKS, None
    for bq in _SWEEP_CANDIDATES:
        for bk in _SWEEP_CANDIDATES:
            if bq > seq or bk > seq or seq % bq or seq % bk:
                continue
            # [bq, bk] f32 score tile + k/v strips must fit VMEM (~16MB)
            vmem = 4 * bq * bk * 3 + 2 * seq * head_dim * 4
            if vmem > 12 * 2**20:
                continue
            try:
                t = step_time(bq, bk)
            except Exception:
                continue  # tile rejected by the compiler: skip
            if best_t is None or t < best_t:
                best, best_t = (bq, bk), t
    best = (_pick_block(seq, best[0]), _pick_block(seq, best[1]))
    _SWEEP_CACHE[key] = best
    _persist_sweep_entry(key, best)
    return best


def _fwd_gqa(q4, k3, v3, mask, causal, block_q=512, block_k=512):
    bhkv, g, s, d = q4.shape
    hkv = bhkv // mask.shape[0]
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    grid = (bhkv, g, s // block_q)
    kernel = functools.partial(_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, gi, i: (b, gi, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, gi, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, gi, i: (b, 0, 0)),
            pl.BlockSpec((None, 1, s),
                         lambda b, gi, i, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, gi, i: (b, gi, i, 0)),
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, gi, i: (b, gi, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhkv, g, s, d), q4.dtype),
            jax.ShapeDtypeStruct((bhkv, g, 1, s), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(q4, k3, v3, mask)


def _bwd_gqa(q4, k3, v3, mask, o4, lse, do4, causal,
             block_q=512, block_k=512):
    bhkv, g, s, d = q4.shape
    hkv = bhkv // mask.shape[0]
    block_q = _pick_block(s, block_q)
    block_k = _pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    # delta_i = do_i · o_i — one fused XLA rowsum, O(S·D)
    delta = jnp.sum(do4.astype(jnp.float32) * o4.astype(jnp.float32),
                    axis=-1)[:, :, None, :]                # [BHkv,G,1,S]

    dq_kernel = functools.partial(_bwd_dq_kernel, block_k=block_k,
                                  causal=causal, scale=scale)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bhkv, g, s // block_q),
        in_specs=[
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, gi, i: (b, gi, i, 0)),   # q
            pl.BlockSpec((None, s, d), lambda b, gi, i: (b, 0, 0)),  # k
            pl.BlockSpec((None, s, d), lambda b, gi, i: (b, 0, 0)),  # v
            pl.BlockSpec((None, None, block_q, d),
                         lambda b, gi, i: (b, gi, i, 0)),   # do
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, gi, i: (b, gi, 0, i)),   # lse
            pl.BlockSpec((None, None, 1, block_q),
                         lambda b, gi, i: (b, gi, 0, i)),   # delta
            pl.BlockSpec((None, 1, s),
                         lambda b, gi, i, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, block_q, d),
                               lambda b, gi, i: (b, gi, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bhkv, g, s, d), q4.dtype),
        interpret=_INTERPRET,
    )(q4, k3, v3, do4, lse, delta, mask)

    dkv_kernel = functools.partial(_bwd_dkv_kernel, block_q=block_q,
                                   causal=causal, scale=scale,
                                   n_groups=g)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bhkv, s // block_k, g),   # g innermost: in-place accumulate
        in_specs=[
            pl.BlockSpec((None, block_k, d),
                         lambda b, j, gi: (b, j, 0)),       # k
            pl.BlockSpec((None, block_k, d),
                         lambda b, j, gi: (b, j, 0)),       # v
            pl.BlockSpec((None, None, s, d),
                         lambda b, j, gi: (b, gi, 0, 0)),   # q (one group)
            pl.BlockSpec((None, None, s, d),
                         lambda b, j, gi: (b, gi, 0, 0)),   # do
            pl.BlockSpec((None, None, 1, s),
                         lambda b, j, gi: (b, gi, 0, 0)),   # lse
            pl.BlockSpec((None, None, 1, s),
                         lambda b, j, gi: (b, gi, 0, 0)),   # delta
            pl.BlockSpec((None, 1, s),
                         lambda b, j, gi, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda b, j, gi: (b, j, 0)),
            pl.BlockSpec((None, block_k, d), lambda b, j, gi: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
            jax.ShapeDtypeStruct((bhkv, s, d), jnp.float32),
        ],
        interpret=_INTERPRET,
    )(k3, v3, q4, do4, lse, delta, mask)
    return dq, dk.astype(k3.dtype), dv.astype(v3.dtype)


# ---------------------------------------------------------------------------
# layout shuffles [B,S,H,D] <-> GQA grid layout
# ---------------------------------------------------------------------------
def _to_gqa(q, k, v):
    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    # q head index = hk * g + gi (repeat_interleave convention)
    q4 = jnp.swapaxes(q, 1, 2).reshape(b * hkv, g, s, d)
    k3 = jnp.swapaxes(k, 1, 2).reshape(b * hkv, s, d)
    v3 = jnp.swapaxes(v, 1, 2).reshape(b * hkv, s, d)
    return q4, k3, v3


def _from_gqa_q(o4, b, s, h, d):
    return jnp.swapaxes(o4.reshape(b, h, s, d), 1, 2)


def _composite(q, k, v, causal, kv_mask=None):
    """XLA reference math on [B,S,H,D] (k/v may have fewer heads)."""
    b, s, h, d = q.shape
    hkv = k.shape[2]
    if hkv != h:
        k = jnp.repeat(k, h // hkv, axis=2)
        v = jnp.repeat(v, h // hkv, axis=2)
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(mask, scores, _NEG)
    if kv_mask is not None:
        scores = jnp.where(kv_mask[:, None, None, :] > 0, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    # fully-masked rows: softmax over all-_NEG scores is uniform, but the
    # Pallas kernel emits exact zeros there (l -> 0 guard) — zero them so
    # kernel and composite agree bit-for-bit in convention
    probs = jnp.where(
        jnp.max(scores, axis=-1, keepdims=True) <= _NEG / 2,
        jnp.zeros_like(probs), probs)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _flash(q, k, v, mask, causal):
    o, _ = _flash_fwd_impl(q, k, v, mask, causal)
    return o


def _flash_fwd_impl(q, k, v, mask, causal):
    b, s, h, d = q.shape
    q4, k3, v3 = _to_gqa(q, k, v)
    bq, bk = get_block_sizes(s, d, causal)
    o4, lse = _fwd_gqa(q4, k3, v3, mask, causal, block_q=bq, block_k=bk)
    return _from_gqa_q(o4, b, s, h, d), (q, k, v, mask, o4, lse)


def _flash_fwd(q, k, v, mask, causal):
    return _flash_fwd_impl(q, k, v, mask, causal)


def _flash_bwd(causal, res, g_out):
    q, k, v, mask, o4, lse = res
    b, s, h, d = q.shape
    hkv = k.shape[2]
    q4, k3, v3 = _to_gqa(q, k, v)
    do4 = jnp.swapaxes(g_out, 1, 2).reshape(b * hkv, h // hkv, s, d)
    bq, bk = get_block_sizes(s, d, causal)
    dq4, dk3, dv3 = _bwd_gqa(q4, k3, v3, mask, o4, lse, do4, causal,
                             block_q=bq, block_k=bk)
    dq = _from_gqa_q(dq4, b, s, h, d).astype(q.dtype)
    dk = jnp.swapaxes(dk3.reshape(b, hkv, s, d), 1, 2)
    dv = jnp.swapaxes(dv3.reshape(b, hkv, s, d), 1, 2)
    return dq, dk, dv, jnp.zeros_like(mask)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, kv_mask=None):
    """q [B,S,H,D]; k/v [B,S,Hkv,D] (GQA native — no head expansion);
    kv_mask optional [B,S] (1 = key attended, 0 = padding). Pallas fused
    fwd+bwd when shapes allow, XLA composite otherwise."""
    b, s, h, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    supported = (s == sk and s % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0)
    if not supported or not flash_attention_available():
        return _composite(q, k, v, causal, kv_mask)
    mask = jnp.ones((b, 1, s), jnp.float32) if kv_mask is None \
        else kv_mask.reshape(b, 1, s).astype(jnp.float32)
    return _flash(q, k, v, mask, causal)
