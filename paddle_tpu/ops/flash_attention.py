"""Pallas TPU flash attention (blockwise online-softmax).

The reference has no training-time fused attention (only the inference
fused/multihead_matmul_op.cu); this kernel is the TPU-native upgrade: the
[B,H,S,S] score matrix never leaves VMEM — each q-block streams k/v-blocks
through the MXU with running max/denominator, so HBM traffic is O(S·D)
instead of O(S²). Backward recomputes attention via the XLA composite
(standard flash recompute strategy; a Pallas backward kernel can slot in
behind the same custom_vjp later).

Layout contract: q, k, v are [B, S, H, D] (paddle flash_attention layout);
internally processed per (batch, head).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional on CPU-only hosts
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

_INTERPRET = False  # set True in tests to run the kernel on CPU


def set_interpret_mode(flag: bool):
    global _INTERPRET
    _INTERPRET = bool(flag)


def flash_attention_available() -> bool:
    if not _HAS_PLTPU:
        return False
    if _INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
               scale: float, q_offset_blocks: int):
    """One (batch*head, q_block) program: online softmax over k blocks.

    q_ref: [block_q, d]; k_ref/v_ref: [S, d] (whole sequence for this head
    in VMEM); o_ref: [block_q, d].
    """
    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    q = q_ref[:].astype(jnp.float32) * scale
    qi = pl.program_id(1)

    m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    q_start = (qi + q_offset_blocks) * block_q

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            sblk = jnp.where(rows >= cols, sblk, -1e30)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only k blocks that intersect the causal triangle for this q block
        last = (q_start + block_q + block_k - 1) // block_k
        n_iter = jnp.minimum(last, n_k)
    else:
        n_iter = n_k
    m, l, acc = jax.lax.fori_loop(0, n_iter, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _fa_forward_bhsd(q, k, v, causal, block_q=256, block_k=256):
    """q,k,v: [BH, S, D] -> out [BH, S, D]. Block sizes must divide S —
    pick the largest power-of-two block ≤ requested that does."""
    bh, s, d = q.shape
    while s % block_q != 0:
        block_q //= 2
    while s % block_k != 0:
        block_k //= 2
    scale = 1.0 / math.sqrt(d)
    grid = (bh, s // block_q)

    kernel = functools.partial(_fa_kernel, block_k=block_k, causal=causal,
                               scale=scale, q_offset_blocks=0)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=_INTERPRET,
    )(q, k, v)


def _composite(q, k, v, causal):
    """XLA reference math on [B,S,H,D]."""
    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    if causal:
        sq, sk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def flash_attention(q, k, v, causal=False):
    """q,k,v: [B, S, H, D]. Fused Pallas forward; recompute backward."""
    b, s, h, d = q.shape
    sk = k.shape[1]
    supported = (s == sk and s % 128 == 0 and (d % 128 == 0 or d == 64))
    if not supported or not flash_attention_available():
        return _composite(q, k, v, causal)
    qf = jnp.swapaxes(q, 1, 2).reshape(b * h, s, d)
    kf = jnp.swapaxes(k, 1, 2).reshape(b * h, s, d)
    vf = jnp.swapaxes(v, 1, 2).reshape(b * h, s, d)
    out = _fa_forward_bhsd(qf, kf, vf, causal)
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)


def _fa_fwd(q, k, v, causal):
    return flash_attention(q, k, v, causal), (q, k, v)


def _fa_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda a, b, c: _composite(a, b, c, causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)
