"""Fused Pallas megakernel for ONE GPT layer decode step.

The decode hot loop (inference.engine) spends each layer step on a chain
of small ops — LayerNorm, qkv projection, cache write, fused attention,
output projection, residual, LayerNorm, MLP up/gelu/down, residual —
and between every pair the [B, H] activations round-trip HBM and XLA
pays a dispatch.  Decode is bandwidth-bound: the useful bytes per layer
step are the layer's parameters (streamed once) and the KV cache strips
(streamed once per slot); everything else is overhead.  This module
fuses the WHOLE layer step into one Pallas kernel — the TPU analogue of
the reference framework fusing per-op dispatch away in its kernel layer
(PAPER.md §1 layers 2-3):

    grid (ns + 1 + nf, B)   # phases outer, slots inner

    phase p == 0        ln_1(x) -> qkv projection -> split q / k_new /
                        v_new into VMEM scratch, init online softmax
    phase p <  ns       stream KV block p of slot b ([block_s, Hkv, D]
                        strips; int8 blocks dequantized IN VMEM after
                        the DMA), online-softmax update for all heads
    phase p == ns       fold the NEW token's k/v (never written to HBM
                        first — it lives in scratch), finalize softmax,
                        output projection, residual, ln_2 into scratch
    phase p >  ns       MLP tile t = p-ns-1: gelu(h2 @ up_t + b_t) @
                        down_t accumulated in scratch; the last tile
                        adds the residual and writes x_out / k_new /
                        v_new back to HBM

With slots innermost, a weight tile is fetched ONCE and reused by every
slot before the phase advances, and each slot's KV blocks stream exactly
once; the only HBM writes of the whole layer step are x_out [B, H] and
the new token's k/v [B, Hkv, D] (the caller scatters those into the
cache, exactly like the composed path).  All intermediates — q, the new
k/v, the online-softmax state, the post-attention residual — live in
VMEM scratch for the kernel's lifetime.

Two layouts, mirroring ops.decode_attention:

- :func:`decode_layer_step` — Static (dense) cache ``[B, cap, Hkv, D]``
  streamed strip by strip, lengths via scalar prefetch.
- :func:`decode_layer_step_paged` — Paged block pool
  ``[NB, bs, Hkv, D]`` streamed through the slot's block table, the
  same scalar-prefetch indirection as ``paged_decode_attention`` (MLP
  phases pin the KV index map to the null block so no stray re-fetch
  rides the weight tiles).

Both accept int8 caches with per-(position, head) f32 scale strips and
dequantize inside the block loop.  The XLA composite (`quantize=` also
routes here — its projections then run ops.quantized_matmul with int8
qmm tiles from the unified tuning table) reproduces the COMPOSED
kernels path op for op, which makes the composed engine the parity
oracle: on CPU the two lower to the same XLA ops and agree bitwise; the
Pallas kernel is tested against it in interpret mode at 1e-5.

Tensor-parallel serving (ISSUE 18): the megakernel STANDS DOWN under
tp>1.  Its whole-layer fusion assumes every projection's full weight is
resident in one kernel's VMEM plan, which contradicts the tp layout
(qkv/up column-split, out/down row-split with a psum between) — the
per-head shard_map treatment that works for the attention-only decode
kernels (ops.decode_attention) cannot cover the row-split matmuls
without growing collectives inside the kernel.  ``gpt.
_megakernel_active`` checks the live mesh and keeps the composed GSPMD
path whenever the tp axis has extent > 1; ``engine.stats
["decode_megakernel"]`` reports what actually runs, so an armed knob
that stood down is visible, not silent.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import importlib

# the package __init__ rebinds sibling names to public functions; fetch
# the modules themselves (their _INTERPRET flags are live state)
_fa = importlib.import_module(__package__ + ".flash_attention")
_da = importlib.import_module(__package__ + ".decode_attention")

__all__ = ["decode_layer_step", "decode_layer_step_paged",
           "decode_megakernel_available", "megakernel_enabled",
           "set_interpret_mode", "LAYER_WEIGHTS"]

_NEG = -1e30
_STATE = {"interpret": None}  # None = follow flash_attention's flag

# the 12 per-layer arrays a fused step consumes, in argument order
LAYER_WEIGHTS = ("ln1_w", "ln1_b", "w_qkv", "b_qkv", "w_out", "b_out",
                 "ln2_w", "ln2_b", "w_up", "b_up", "w_down", "b_down")

# conservative VMEM budget for the fused kernel's resident blocks
# (~16MB/core on v5e; leave headroom for Mosaic's own allocations and
# double buffering of the streamed operands, which the estimate below
# already counts at 2x)
_VMEM_BUDGET = int(os.environ.get("PADDLE_TPU_MEGAKERNEL_VMEM",
                                  14 * 2**20))


def set_interpret_mode(flag):
    """True/False force interpret mode; None follows
    flash_attention.set_interpret_mode (one test switch for all
    kernels)."""
    _STATE["interpret"] = flag


def _interpret() -> bool:
    if _STATE["interpret"] is not None:
        return bool(_STATE["interpret"])
    return _fa._INTERPRET


def decode_megakernel_available() -> bool:
    """Pallas fused path available (needs scalar prefetch, same surface
    as the paged decode kernel)."""
    if not _fa._HAS_PLTPU or _fa.pltpu is None:
        return False
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def megakernel_enabled(cfg) -> bool:
    """The serving knob: PADDLE_TPU_DECODE_MEGAKERNEL overrides (any
    value but "0" arms it), else ``cfg.decode_megakernel``.  Read at
    trace time — the engine compiles its decode executable once per
    process, so the flag is process-stable by construction."""
    env = os.environ.get("PADDLE_TPU_DECODE_MEGAKERNEL")
    if env is not None:
        return env != "0"
    return bool(getattr(cfg, "decode_megakernel", False))


def _pick_blocks(seq_extent: int, ffn: int, qkv_cols: int = 0,
                 h: int = 0):
    """(block_s, block_f, block_q, block_o) for the KV stream / MLP
    tiles / qkv-projection column tiles / out-projection row tiles; env
    PADDLE_TPU_MEGAKERNEL_BLOCKS="s,f[,q,o]" overrides, clamped to
    divide.  Tiling the qkv/out weight fetches (instead of keeping both
    matrices resident) is what lets gpt3-350m-class layers fit the VMEM
    gate — a tile is fetched once per phase with slots innermost, so
    the HBM traffic is unchanged."""
    env = os.environ.get("PADDLE_TPU_MEGAKERNEL_BLOCKS", "").strip()
    want_s, want_f, want_q, want_o = 512, 256, 512, 512
    if env:
        try:
            parts = [int(x) for x in env.split(",")]
            if len(parts) >= 2:
                want_s, want_f = parts[0], parts[1]
            if len(parts) >= 4:
                want_q, want_o = parts[2], parts[3]
        except ValueError:
            pass
    return (_fa._pick_block(seq_extent, want_s),
            _fa._pick_block(ffn, want_f),
            _fa._pick_block(qkv_cols, want_q) if qkv_cols else 0,
            _fa._pick_block(h, want_o) if h else 0)


def _vmem_estimate(h, kvd, f, block_s, block_f, block_q, block_o, hkv,
                   d, w_item, kv_item, quantized, batch):
    """Rough resident-VMEM bytes: streamed operands counted at 2x
    (double buffering) — which, after the qkv/out tiling, is EVERY
    weight matrix; only the LayerNorm/bias vectors stay resident —
    plus the per-slot scratch."""
    resident = 8 * h * w_item                    # ln1/ln2 w+b, bout, bdown
    streamed = 2 * (h * block_q + block_q) * w_item          # qkv tile
    streamed += 2 * block_o * h * w_item                     # out tile
    streamed += 2 * (h * block_f + block_f + block_f * h) * w_item  # mlp
    streamed += 2 * 2 * block_s * hkv * d * kv_item          # k+v strips
    if quantized:
        streamed += 2 * 2 * block_s * hkv * 4                # scale strips
    qkv_cols = h + 2 * kvd
    heads = h // d
    scratch = batch * (qkv_cols + 5 * h + heads * d) * 4 \
        + batch * 2 * heads * 128 * 4
    return resident + streamed + scratch


def _gelu_tanh(x):
    # jax.nn.gelu(approximate=True): the tanh form the composed GPTMLP
    # uses — the kernel must match it, not erf gelu
    c = math.sqrt(2.0 / math.pi)
    return 0.5 * x * (1.0 + jnp.tanh(c * (x + 0.044715 * x * x * x)))


# ---------------------------------------------------------------------------
# the fused kernel (shared body; dense and paged differ only in how KV
# blocks are addressed, which the BlockSpec index maps own)
# ---------------------------------------------------------------------------
def _mega_kernel(len_ref, x_ref, ln1w_ref, ln1b_ref, wqkv_ref, bqkv_ref,
                 wout_ref, bout_ref, ln2w_ref, ln2b_ref, wup_ref, bup_ref,
                 wdown_ref, bdown_ref, k_ref, v_ref, ks_ref, vs_ref,
                 xo_ref, kn_ref, vn_ref,
                 qkv_scr, m_scr, l_scr, acc_scr,
                 attn_scr, o_scr, x2_scr, h2_scr, mlp_scr,
                 *, nq: int, ns: int, no: int, nf: int, block_s: int,
                 block_q: int, block_o: int, heads: int, hkv: int,
                 d: int, h: int, scale: float, eps: float, cap: int,
                 quantized: bool, paged: bool):
    """One (phase, slot) program.  Scalar-prefetched ``len_ref`` carries
    per-slot lengths (EXCLUDING the new token, engine convention); for
    the paged layout the block table already acted inside the index
    maps, so the body only sees [block_s, Hkv, D] strips either way.
    ``ks_ref``/``vs_ref`` are the f32 scale strips of an int8 cache
    (aliases of k_ref/v_ref in the fp path, unread).

    Phase layout (nq qkv column tiles, ns KV blocks, 1 softmax
    finalize, no out-proj row tiles, nf MLP tiles — every weight
    matrix STREAMS tile by tile, the widened-VMEM-gate satellite):

        [0, nq)                qkv tile t = p: ln1(x) recomputed (one
                               [1,H] VPU pass per tile — noise), one
                               [H, block_q] weight tile, result into
                               the qkv scratch column slice
        [nq, nq+ns)            KV block j = p-nq, online softmax
        nq+ns                  fold new token, finalize -> attn scratch
        (nq+ns, nq+ns+no]      out-proj row tile t accumulates into the
                               o scratch; the LAST tile adds residual +
                               bias and runs ln2
        (nq+ns+no, +nf]        MLP tiles; the last one also writes"""
    p = pl.program_id(0)
    b = pl.program_id(1)
    g = heads // hkv
    kvd = hkv * d
    bsl = pl.ds(b, 1)

    # the slot's logical write position for the new token: the composed
    # path clamps to cap-1 (dense) so the mask must clamp identically
    length = len_ref[b]
    idx = jnp.minimum(length, cap - 1) if not paged else length

    @pl.when(p < nq)
    def _qkv_tile():
        xb = x_ref[...].astype(jnp.float32)               # [1, H]
        mu = jnp.mean(xb, axis=-1, keepdims=True)
        var = jnp.mean((xb - mu) ** 2, axis=-1, keepdims=True)
        h1 = (xb - mu) * jax.lax.rsqrt(var + eps)
        h1 = h1 * ln1w_ref[...].astype(jnp.float32) + \
            ln1b_ref[...].astype(jnp.float32)
        tile = jax.lax.dot_general(
            h1.astype(wqkv_ref.dtype), wqkv_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + \
            bqkv_ref[...].astype(jnp.float32)             # [1, block_q]
        qkv_scr[bsl, pl.ds(p * block_q, block_q)] = tile

    @pl.when(p == nq - 1)
    def _attend_init():
        m_scr[bsl] = jnp.full((1,) + m_scr.shape[1:], _NEG, jnp.float32)
        l_scr[bsl] = jnp.zeros((1,) + l_scr.shape[1:], jnp.float32)
        acc_scr[bsl] = jnp.zeros((1, heads, d), jnp.float32)

    @pl.when((p >= nq) & (p < nq + ns))
    def _attend():
        q = qkv_scr[bsl, :h].reshape(heads, d)            # [heads, d] f32
        pos = (p - nq) * block_s + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_s), 1)
        valid = pos < idx                                 # [1, block_s]
        scores, vals = [], []
        for hk in range(hkv):
            kh = k_ref[:, hk, :]                          # [block_s, d]
            vh = v_ref[:, hk, :]
            if quantized:
                kh = kh.astype(jnp.float32) * ks_ref[:, hk][:, None]
                vh = vh.astype(jnp.float32) * vs_ref[:, hk][:, None]
            qg = q[hk * g:(hk + 1) * g].astype(kh.dtype)  # [g, d]
            scores.append(jax.lax.dot_general(
                qg, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32))      # [g, block_s]
            vals.append(vh)
        sblk = jnp.concatenate(scores, axis=0) * scale    # [heads, bs]
        sblk = jnp.where(valid, sblk, _NEG)
        m_prev = m_scr[bsl][0][:, :1]                     # [heads, 1]
        l_prev = l_scr[bsl][0][:, :1]
        acc_prev = acc_scr[bsl][0]                        # [heads, d]
        m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
        pmat = jnp.exp(sblk - m_new)
        pmat = jnp.where(sblk <= _NEG / 2, 0.0, pmat)     # fully masked
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(pmat, axis=1, keepdims=True)
        accs = [jax.lax.dot_general(
            pmat[hk * g:(hk + 1) * g].astype(vals[hk].dtype), vals[hk],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) for hk in range(hkv)]
        acc_new = acc_prev * alpha + jnp.concatenate(accs, axis=0)
        m_scr[bsl] = jnp.broadcast_to(m_new[None, :, :],
                                      (1,) + m_scr.shape[1:])
        l_scr[bsl] = jnp.broadcast_to(l_new[None, :, :],
                                      (1,) + l_scr.shape[1:])
        acc_scr[bsl] = acc_new[None]

    @pl.when(p == nq + ns)
    def _finalize():
        q = qkv_scr[bsl, :h].reshape(heads, d)            # [heads, d]
        kn = qkv_scr[bsl, h:h + kvd].reshape(hkv, d)      # [hkv, d] f32
        vn = qkv_scr[bsl, h + kvd:].reshape(hkv, d)
        if quantized:
            # the composed path STORES the new k/v quantized and attends
            # the dequantized codes; reproduce that round trip exactly
            kamax = jnp.maximum(jnp.max(jnp.abs(kn), axis=-1,
                                        keepdims=True), 1e-8)
            vamax = jnp.maximum(jnp.max(jnp.abs(vn), axis=-1,
                                        keepdims=True), 1e-8)
            ksc, vsc = kamax / 127.0, vamax / 127.0
            kn = jnp.clip(jnp.round(kn / ksc), -127.0, 127.0) * ksc
            vn = jnp.clip(jnp.round(vn / vsc), -127.0, 127.0) * vsc
        kn_rep = jnp.repeat(kn, g, axis=0)                # [heads, d]
        vn_rep = jnp.repeat(vn, g, axis=0)
        s_new = jnp.sum(q * kn_rep, axis=-1,
                        keepdims=True) * scale            # [heads, 1]
        m_prev = m_scr[bsl][0][:, :1]
        l_prev = l_scr[bsl][0][:, :1]
        acc_prev = acc_scr[bsl][0]
        m_new = jnp.maximum(m_prev, s_new)
        pnew = jnp.exp(s_new - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + pnew
        acc = acc_prev * alpha + pnew * vn_rep
        attn = acc / jnp.maximum(l_new, 1e-30)            # [heads, d]
        attn_scr[bsl] = attn.reshape(1, 1, h)
        o_scr[bsl] = jnp.zeros((1, 1, h), jnp.float32)

    @pl.when((p > nq + ns) & (p <= nq + ns + no))
    def _out_tile():
        t = p - nq - ns - 1
        attn_t = attn_scr[bsl, :, pl.ds(t * block_o, block_o)] \
            .reshape(1, block_o)
        part = jax.lax.dot_general(
            attn_t.astype(wout_ref.dtype), wout_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [1, H]
        o_scr[bsl] = o_scr[bsl] + part[None]

    @pl.when(p == nq + ns + no)
    def _residual_ln2():
        # the LAST out-proj tile just accumulated above (source order);
        # close the attention half: bias + residual + ln2
        o = o_scr[bsl][0] + bout_ref[...].astype(jnp.float32)
        x2 = x_ref[...].astype(jnp.float32) + o
        mu = jnp.mean(x2, axis=-1, keepdims=True)
        var = jnp.mean((x2 - mu) ** 2, axis=-1, keepdims=True)
        h2 = (x2 - mu) * jax.lax.rsqrt(var + eps)
        h2 = h2 * ln2w_ref[...].astype(jnp.float32) + \
            ln2b_ref[...].astype(jnp.float32)
        x2_scr[bsl] = x2[None]
        h2_scr[bsl] = h2[None]
        mlp_scr[bsl] = jnp.zeros((1, 1, h), jnp.float32)

    @pl.when(p > nq + ns + no)
    def _mlp():
        h2 = h2_scr[bsl][0]                               # [1, H] f32
        u = jax.lax.dot_general(
            h2.astype(wup_ref.dtype), wup_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) + \
            bup_ref[...].astype(jnp.float32)              # [1, block_f]
        act = _gelu_tanh(u)
        part = jax.lax.dot_general(
            act.astype(wdown_ref.dtype), wdown_ref[...],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [1, H]
        mlp_scr[bsl] = mlp_scr[bsl] + part[None]

    @pl.when(p == nq + ns + no + nf)
    def _write():
        # the LAST visit of slot b's output blocks: earlier phases flush
        # whatever the buffers held, but this write lands last and wins.
        # k_new/v_new leave RAW (pre-quantization) — the caller owns the
        # cache write, exactly like the composed path
        xo_ref[...] = (x2_scr[bsl][0] + mlp_scr[bsl][0] +
                       bdown_ref[...].astype(jnp.float32)
                       ).astype(xo_ref.dtype)
        kn_ref[...] = qkv_scr[bsl, h:h + kvd].reshape(
            1, hkv, d)[0].astype(kn_ref.dtype)
        vn_ref[...] = qkv_scr[bsl, h + kvd:].reshape(
            1, hkv, d)[0].astype(vn_ref.dtype)


def _run_mega(x, w, k_src, v_src, ks_src, vs_src, lengths, *, ns, cap,
              eps, quantized, paged, kv_map_factory, sc_map_factory,
              extra_scalars=()):
    """Shared pallas_call wrapper: builds grid/specs around the kernel
    body.  ``kv_map_factory``/``sc_map_factory`` take the qkv-tile
    phase count ``nq`` (the KV phases start at ``nq``) and return the
    layout's index map (dense strip walk vs paged table indirection)."""
    pltpu = _fa.pltpu
    (ln1_w, ln1_b, w_qkv, b_qkv, w_out, b_out,
     ln2_w, ln2_b, w_up, b_up, w_down, b_down) = w
    bsz, h = x.shape
    hkv, d = k_src.shape[-2], k_src.shape[-1]
    kvd = hkv * d
    # q width is the qkv columns minus the two kv blocks; head count
    # from the cache head_dim
    heads = (w_qkv.shape[1] - 2 * kvd) // d
    f = w_up.shape[1]
    qkv_cols = h + 2 * kvd
    if paged:
        block_s = k_src.shape[1]          # one pool block per phase
        _, block_f, block_q, block_o = _pick_blocks(block_s, f,
                                                    qkv_cols, h)
    else:
        block_s, block_f, block_q, block_o = _pick_blocks(
            k_src.shape[1], f, qkv_cols, h)
    nq = qkv_cols // block_q
    no = h // block_o
    nf = f // block_f
    np_total = nq + ns + 1 + no + nf
    scale = 1.0 / math.sqrt(d)
    kv_index_map = kv_map_factory(nq)
    sc_index_map = sc_map_factory(nq)

    def vec2(a):
        return a.reshape(1, -1)

    n_scal = 1 + len(extra_scalars)
    # weight specs: every matrix streams tile by tile — qkv columns
    # during the leading phases, out rows after the softmax finalize,
    # up/down during the MLP phases; only the LN/bias vectors keep a
    # constant block index (one fetch, resident)
    def _const(shape):
        return pl.BlockSpec(shape, lambda p, b, *s: (0,) * len(shape))

    def _tile_qkv(p, b, *s):
        return (0, jnp.clip(p, 0, nq - 1))

    def _tile_out(p, b, *s):
        return (jnp.clip(p - nq - ns - 1, 0, no - 1), 0)

    def _tile_up(p, b, *s):
        return (0, jnp.clip(p - nq - ns - no - 1, 0, nf - 1))

    def _tile_down(p, b, *s):
        return (jnp.clip(p - nq - ns - no - 1, 0, nf - 1), 0)

    if quantized:
        sc_spec = pl.BlockSpec((None, block_s, hkv), sc_index_map)
    else:
        # unread placeholder: one block pinned at index 0, fetched once
        sc_spec = pl.BlockSpec((None, block_s, hkv),
                               lambda p, b, *s: (0, 0, 0))
    in_specs = [
        pl.BlockSpec((1, h), lambda p, b, *s: (b, 0)),          # x
        _const((1, h)), _const((1, h)),                         # ln1 w/b
        pl.BlockSpec((h, block_q), _tile_qkv),                  # qkv w
        pl.BlockSpec((1, block_q), _tile_qkv),                  # qkv b
        pl.BlockSpec((block_o, h), _tile_out),                  # out w
        _const((1, h)),                                         # out b
        _const((1, h)), _const((1, h)),                         # ln2 w/b
        pl.BlockSpec((h, block_f), _tile_up),                   # up w
        pl.BlockSpec((1, block_f), _tile_up),                   # up b
        pl.BlockSpec((block_f, h), _tile_down),                 # down w
        _const((1, h)),                                         # down b
        pl.BlockSpec((None, block_s, hkv, d), kv_index_map),    # k
        pl.BlockSpec((None, block_s, hkv, d), kv_index_map),    # v
        sc_spec,                                                # k scale
        sc_spec,                                                # v scale
    ]
    out_specs = [
        pl.BlockSpec((1, h), lambda p, b, *s: (b, 0)),
        pl.BlockSpec((None, hkv, d), lambda p, b, *s: (b, 0, 0)),
        pl.BlockSpec((None, hkv, d), lambda p, b, *s: (b, 0, 0)),
    ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_scal,
        grid=(np_total, bsz),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((bsz, qkv_cols), jnp.float32),    # qkv (q|k|v new)
            pltpu.VMEM((bsz, heads, 128), jnp.float32),  # running max
            pltpu.VMEM((bsz, heads, 128), jnp.float32),  # running denom
            pltpu.VMEM((bsz, heads, d), jnp.float32),    # attn accum
            pltpu.VMEM((bsz, 1, h), jnp.float32),        # attn out
            pltpu.VMEM((bsz, 1, h), jnp.float32),        # out-proj accum
            pltpu.VMEM((bsz, 1, h), jnp.float32),        # x2 residual
            pltpu.VMEM((bsz, 1, h), jnp.float32),        # ln2 output
            pltpu.VMEM((bsz, 1, h), jnp.float32),        # mlp accum
        ],
    )
    kernel = functools.partial(
        _mega_kernel, nq=nq, ns=ns, no=no, nf=nf, block_s=block_s,
        block_q=block_q, block_o=block_o, heads=heads,
        hkv=hkv, d=d, h=h, scale=scale, eps=eps, quantized=quantized,
        paged=paged, cap=cap)
    n_extra = len(extra_scalars)
    if n_extra:
        # the body only consumes lengths; extra scalar refs (the paged
        # block table) act entirely inside the BlockSpec index maps
        body = lambda *a: kernel(*a[n_extra:])   # noqa: E731
    else:
        body = kernel
    if quantized:
        ks_in, vs_in = (ks_src.astype(jnp.float32),
                        vs_src.astype(jnp.float32))
    else:
        # unread by the kernel; one-block placeholders keep arity fixed
        ks_in = jnp.zeros((1, block_s, hkv), jnp.float32)
        vs_in = ks_in
    scalars = tuple(jnp.asarray(s, jnp.int32) for s in extra_scalars) + \
        (lengths.astype(jnp.int32),)
    out_shapes = [
        jax.ShapeDtypeStruct((bsz, h), x.dtype),
        jax.ShapeDtypeStruct((bsz, hkv, d), x.dtype),
        jax.ShapeDtypeStruct((bsz, hkv, d), x.dtype),
    ]
    return pl.pallas_call(
        body,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=_interpret(),
    )(*scalars, x, vec2(ln1_w), vec2(ln1_b), w_qkv, vec2(b_qkv),
      w_out, vec2(b_out), vec2(ln2_w), vec2(ln2_b), w_up, vec2(b_up),
      w_down, vec2(b_down), k_src, v_src, ks_in, vs_in)


# ---------------------------------------------------------------------------
# composite fallback: the composed kernels path, op for op
# ---------------------------------------------------------------------------
def _mm(x2, w, bias, quantize):
    """The projection math of the composed path: F.linear, or the
    fake-quant forward when the model trains/serves quantized (same
    numbers as ops.quantized_matmul — int8 qmm tiles from the unified
    tuning table when the Pallas qmm kernel engages)."""
    if quantize:
        from .quantized_matmul import quantized_matmul
        y = quantized_matmul(x2, w, dtype=quantize, out_dtype=x2.dtype)
    else:
        y = jnp.matmul(x2, w)
    if bias is not None:
        y = y + bias
    return y


def _ln_f32(x2, w, bias, eps):
    xf = x2.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    out = out * w + bias
    return out.astype(x2.dtype)


def _split_qkv(qkv, h, hkv, d):
    bsz = qkv.shape[0]
    kvd = hkv * d
    heads = (qkv.shape[1] - 2 * kvd) // d
    q = qkv[:, :h].reshape(bsz, heads, d)
    k_new = qkv[:, h:h + kvd].reshape(bsz, hkv, d)
    v_new = qkv[:, h + kvd:].reshape(bsz, hkv, d)
    return q, k_new, v_new


def _composite(x, w, lengths, attend, *, quantize, eps, hkv, d):
    """Shared composite body; ``attend(q, k_new, v_new)`` runs the
    layout's attention (dense/paged) over the cache WITH the new token
    folded in, mirroring the composed write-then-attend order."""
    (ln1_w, ln1_b, w_qkv, b_qkv, w_out, b_out,
     ln2_w, ln2_b, w_up, b_up, w_down, b_down) = w
    h = x.shape[1]
    h1 = _ln_f32(x, ln1_w, ln1_b, eps)
    qkv = _mm(h1, w_qkv, b_qkv, quantize)
    q, k_new, v_new = _split_qkv(qkv, h, hkv, d)
    attn = attend(q, k_new, v_new)                  # [B, heads, d]
    o = _mm(attn.reshape(x.shape[0], -1).astype(x.dtype), w_out, None,
            quantize) + b_out
    x2 = x + o.astype(x.dtype)
    h2 = _ln_f32(x2, ln2_w, ln2_b, eps)
    u = _mm(h2, w_up, b_up, quantize)
    act = jax.nn.gelu(u, approximate=True)
    mlp = _mm(act, w_down, None, quantize) + b_down
    x_out = x2 + mlp.astype(x.dtype)
    return x_out, k_new, v_new


def _dense_attend(q, k_new, v_new, k_cache, v_cache, lengths, k_scale,
                  v_scale):
    bsz = q.shape[0]
    cap = k_cache.shape[1]
    idx = jnp.minimum(lengths.astype(jnp.int32), cap - 1)
    rows = jnp.arange(bsz)
    if k_scale is not None:
        from .quantized_matmul import kv_quant_mode, quantize_kv
        mode = kv_quant_mode(k_cache.dtype)
        kq, ks = quantize_kv(k_new, mode)
        vq, vs = quantize_kv(v_new, mode)
        k_eff = k_cache.at[rows, idx].set(kq)
        v_eff = v_cache.at[rows, idx].set(vq)
        ks_eff = k_scale.at[rows, idx].set(ks.astype(k_scale.dtype))
        vs_eff = v_scale.at[rows, idx].set(vs.astype(v_scale.dtype))
        return _da.decode_attention(q, k_eff, v_eff, idx + 1, ks_eff,
                                   vs_eff)
    k_eff = k_cache.at[rows, idx].set(k_new.astype(k_cache.dtype))
    v_eff = v_cache.at[rows, idx].set(v_new.astype(v_cache.dtype))
    return _da.decode_attention(q.astype(k_cache.dtype), k_eff, v_eff,
                               idx + 1).astype(q.dtype)


def _paged_attend(q, k_new, v_new, k_pool, v_pool, tables, lengths,
                  k_scale, v_scale):
    bsz = q.shape[0]
    bs = k_pool.shape[1]
    mb = tables.shape[1]
    lens = lengths.astype(jnp.int32)
    blk_pos = jnp.minimum(lens // bs, mb - 1)
    off = lens % bs
    rows = jnp.arange(bsz)
    blk = tables[rows, blk_pos]
    if k_scale is not None:
        from .quantized_matmul import kv_quant_mode, quantize_kv
        mode = kv_quant_mode(k_pool.dtype)
        kq, ks = quantize_kv(k_new, mode)
        vq, vs = quantize_kv(v_new, mode)
        k_eff = k_pool.at[blk, off].set(kq)
        v_eff = v_pool.at[blk, off].set(vq)
        ks_eff = k_scale.at[blk, off].set(ks.astype(k_scale.dtype))
        vs_eff = v_scale.at[blk, off].set(vs.astype(v_scale.dtype))
        return _da.paged_decode_attention(q, k_eff, v_eff, tables,
                                         lens + 1, ks_eff, vs_eff)
    k_eff = k_pool.at[blk, off].set(k_new.astype(k_pool.dtype))
    v_eff = v_pool.at[blk, off].set(v_new.astype(v_pool.dtype))
    return _da.paged_decode_attention(
        q.astype(k_pool.dtype), k_eff, v_eff, tables,
        lens + 1).astype(q.dtype)


def _fused_supported(x, w, hkv, d, block_s, quantize, kv_dtype,
                     kv_item, quantized):
    (ln1_w, ln1_b, w_qkv, b_qkv, w_out, b_out,
     ln2_w, ln2_b, w_up, b_up, w_down, b_down) = w
    h = x.shape[1]
    f = w_up.shape[1]
    kvd = hkv * d
    heads = (w_qkv.shape[1] - 2 * kvd) // d
    if quantize:
        # quantized COMPUTE runs the composite (whose projections take
        # the int8 qmm path with tuned tiles); the fused kernel serves
        # the fp-compute case, with or without an int8 KV cache
        return False
    if quantized and kv_dtype != jnp.int8:
        return False        # fp8 caches ride the composite
    if heads * d != h or heads % hkv:
        return False
    if h % 128 or f % 128 or (d != 64 and d % 128):
        return False
    if block_s % 128:
        return False
    _, block_f, block_q, block_o = _pick_blocks(block_s, f,
                                                h + 2 * kvd, h)
    w_item = jnp.dtype(w_qkv.dtype).itemsize
    est = _vmem_estimate(h, kvd, f, block_s, block_f, block_q, block_o,
                         hkv, d, w_item, kv_item, quantized, x.shape[0])
    if not _interpret() and est > _VMEM_BUDGET:
        return False
    return True


def decode_layer_step(x, w, k_cache, v_cache, lengths, k_scale=None,
                      v_scale=None, *, quantize=None, eps: float = 1e-5):
    """ONE fused GPT layer decode step over a Static (dense) KV cache.

    x ``[B, H]`` — the residual stream at this layer for the new token;
    ``w`` — the 12 per-layer arrays in :data:`LAYER_WEIGHTS` order;
    k_cache/v_cache ``[B, cap, Hkv, D]`` — the cache BEFORE the new
    token is written (the kernel folds the new token's k/v from VMEM;
    the CALLER scatters the returned ``k_new``/``v_new`` into the cache,
    exactly like the composed path does); lengths ``[B]`` int32 tokens
    already cached (excluding the new one).  int8 caches pass their
    ``[B, cap, Hkv]`` f32 scale planes.  Returns
    ``(x_out [B, H], k_new [B, Hkv, D] f32, v_new)``.

    Pallas fused kernel when shapes/VMEM allow, XLA composite (the
    composed kernels path op for op — the parity oracle) otherwise;
    ``quantize`` (int8 compute) always routes the composite, whose
    projections then run the int8 qmm kernel with tiles from the
    unified tuning table.
    """
    hkv, d = k_cache.shape[2], k_cache.shape[3]
    quantized = k_scale is not None
    cap = k_cache.shape[1]
    block_s = _pick_blocks(cap, w[8].shape[1])[0]
    supported = (cap % block_s == 0 and
                 _fused_supported(x, w, hkv, d, block_s, quantize,
                                  k_cache.dtype,
                                  jnp.dtype(k_cache.dtype).itemsize,
                                  quantized))
    if not supported or not decode_megakernel_available():
        attend = functools.partial(_dense_attend, k_cache=k_cache,
                                   v_cache=v_cache, lengths=lengths,
                                   k_scale=k_scale, v_scale=v_scale)
        return _composite(x, w, lengths, attend, quantize=quantize,
                          eps=eps, hkv=hkv, d=d)
    ns = cap // block_s

    def kv_maps(nq):
        def kv_map(p, b, lens):
            in_kv = (p >= nq) & (p < nq + ns)
            return (jnp.where(in_kv, b, 0),
                    jnp.clip(p - nq, 0, ns - 1), 0, 0)
        return kv_map

    def sc_maps(nq):
        def sc_map(p, b, lens):
            in_kv = (p >= nq) & (p < nq + ns)
            return (jnp.where(in_kv, b, 0),
                    jnp.clip(p - nq, 0, ns - 1), 0)
        return sc_map

    return _run_mega(x, w, k_cache, v_cache, k_scale, v_scale, lengths,
                     ns=ns, cap=cap, eps=eps, quantized=quantized,
                     paged=False, kv_map_factory=kv_maps,
                     sc_map_factory=sc_maps)


def decode_layer_step_paged(x, w, k_pool, v_pool, tables, lengths,
                            k_scale=None, v_scale=None, *, quantize=None,
                            eps: float = 1e-5):
    """ONE fused GPT layer decode step over a PAGED KV cache: the same
    fused body as :func:`decode_layer_step`, with the slot's KV blocks
    resolved through its scalar-prefetched block table (the
    ``paged_decode_attention`` indirection) — MLP phases pin the index
    map to the null block so the weight-tile phases never re-stream KV.
    tables ``[B, MB]`` int32; lengths EXCLUDE the new token.  Returns
    ``(x_out, k_new, v_new)`` — the caller scatters the new k/v at
    ``(tables[b, lengths[b]//bs], lengths[b]%bs)``."""
    hkv, d = k_pool.shape[2], k_pool.shape[3]
    quantized = k_scale is not None
    bs = k_pool.shape[1]
    mb = tables.shape[1]
    supported = _fused_supported(x, w, hkv, d, bs, quantize,
                                 k_pool.dtype,
                                 jnp.dtype(k_pool.dtype).itemsize,
                                 quantized)
    if not supported or not decode_megakernel_available():
        attend = functools.partial(_paged_attend, k_pool=k_pool,
                                   v_pool=v_pool, tables=tables,
                                   lengths=lengths, k_scale=k_scale,
                                   v_scale=v_scale)
        return _composite(x, w, lengths, attend, quantize=quantize,
                          eps=eps, hkv=hkv, d=d)

    def kv_maps(nq):
        def kv_map(p, b, tbl, lens):
            blk = tbl[b, jnp.clip(p - nq, 0, mb - 1)]
            in_kv = (p >= nq) & (p < nq + mb)
            return (jnp.where(in_kv, blk, 0), 0, 0, 0)
        return kv_map

    def sc_maps(nq):
        def sc_map(p, b, tbl, lens):
            blk = tbl[b, jnp.clip(p - nq, 0, mb - 1)]
            in_kv = (p >= nq) & (p < nq + mb)
            return (jnp.where(in_kv, blk, 0), 0, 0)
        return sc_map

    return _run_mega(x, w, k_pool, v_pool, k_scale, v_scale, lengths,
                     ns=mb, cap=mb * bs, eps=eps, quantized=quantized,
                     paged=True, kv_map_factory=kv_maps,
                     sc_map_factory=sc_maps,
                     extra_scalars=(tables,))
