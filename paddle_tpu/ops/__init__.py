"""paddle_tpu.ops — custom Pallas TPU kernels.

The reference's equivalent is the C++/CUDA operator library
(/root/reference/paddle/fluid/operators/); here the op library is the XLA
op set (paddle_tpu.tensor / nn.functional lowerings), and this package
holds only the kernels XLA won't produce on its own — fused attention
today, with room for fused optimizers / collectives-overlapped matmuls.
"""
from .flash_attention import (  # noqa: F401
    flash_attention, flash_attention_available, get_block_sizes,
    set_interpret_mode)
from .decode_attention import (  # noqa: F401
    chunk_prefill_attention, decode_attention,
    decode_attention_available, decode_attention_window,
    paged_chunk_prefill_attention, paged_decode_attention,
    paged_decode_attention_available, paged_decode_attention_window)
from .fused_cross_entropy import (  # noqa: F401
    fused_linear_cross_entropy, pick_vocab_block)
from .quantized_matmul import (  # noqa: F401
    quantized_matmul, quantized_matmul_available, fake_quant_matmul,
    quantize_channel, quantize_kv, dequantize_kv, get_qmm_tiles)
from .decode_megakernel import (  # noqa: F401
    decode_layer_step, decode_layer_step_paged,
    decode_megakernel_available, megakernel_enabled)
