"""AQT-style int8 (fp8-ready) quantized matmul + KV-cache quantization.

The bench trajectory stalled at ~35% MFU with the step time dominated by
bf16 matmul FLOPs and, on the serving side, by KV bytes streamed from
HBM.  Both halve under 8-bit arithmetic — the v5e MXU runs int8 at 2×
the bf16 rate, and an int8 KV cache moves half the bytes per decode
step.  This module is the compute half of that attack (the KV half
lives in ops/decode_attention.py + the cache classes):

- :func:`quantize_channel` / :func:`quantize_kv` — symmetric amax
  scaling.  ``quantize_channel`` scales per channel along a named axis
  (per token row for activations, per output column for weights);
  ``quantize_kv`` scales per (position, head) over the trailing
  head_dim axis — the granularity the decode kernels dequantize at.
- :func:`quantized_matmul` — y ≈ (q_x · q_w) · s_x · s_w.  A Pallas TPU
  kernel (int8 MXU dots, int32 accumulation, f32 rescale; tile sizes
  from the unified tuning table) with an XLA ``dot_general`` composite
  fallback that is the CPU parity oracle: the int8 path accumulates in
  int32 (exact — f32 would lose bits past 2^24), the fp8 path in f32
  via ``preferred_element_type``.
- :func:`fake_quant_matmul` — the AQT-style training op: forward runs
  the quantized matmul, backward is the straight-through estimator
  (grads flow through the DEQUANTIZED operands as if quantization were
  identity), so ``GPTConfig(quantize='int8')`` trains through quantized
  forward matmuls without touching the optimizer or the parameters'
  dtype.  Equivalent to ``fq(x) @ fq(w)`` with
  ``fq(t) = t + stop_gradient(qdq(t) - t)`` — the reference the tests
  check the custom VJP against.

fp8 readiness: every helper accepts ``dtype='fp8'`` (E4M3) when this
jax build ships ``jnp.float8_e4m3fn``; the Pallas kernel currently
serves int8 only and fp8 rides the composite.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import importlib

# live view of the sibling module's mutable interpret flag (the package
# __init__ rebinds `flash_attention` to the public function)
_fa = importlib.import_module(__package__ + ".flash_attention")

__all__ = ["quantized_matmul", "quantized_matmul_available",
           "fake_quant_matmul", "quantize_channel", "quantize_kv",
           "dequantize_kv", "kv_storage_dtype", "kv_quant_supported",
           "kv_quant_mode", "resolve_kv_quant", "get_qmm_tiles",
           "autotune_qmm_sweep", "QUANT_DTYPES"]

QUANT_DTYPES = ("int8", "fp8")
_EPS = 1e-8


def _check_mode(dtype: str) -> str:
    if dtype not in QUANT_DTYPES:
        raise ValueError(f"quantize dtype must be one of {QUANT_DTYPES}, "
                         f"got {dtype!r}")
    if dtype == "fp8" and not hasattr(jnp, "float8_e4m3fn"):
        raise ValueError("quantize='fp8' needs a jax with "
                         "jnp.float8_e4m3fn; this build has none — "
                         "use 'int8'")
    return dtype


def _qmax(dtype: str) -> float:
    return 127.0 if dtype == "int8" else 448.0   # E4M3 finite max


def kv_storage_dtype(dtype: str):
    """The jnp storage dtype for a quantized KV cache."""
    _check_mode(dtype)
    return jnp.int8 if dtype == "int8" else jnp.float8_e4m3fn


def kv_quant_supported(dtype) -> bool:
    """True when `dtype` names a usable quantized-KV mode here."""
    try:
        _check_mode(dtype)
        return True
    except ValueError:
        return False


def kv_quant_mode(storage_dtype) -> str:
    """Inverse of :func:`kv_storage_dtype`: the mode name for a
    quantized cache's storage dtype."""
    if storage_dtype == jnp.int8:
        return "int8"
    if hasattr(jnp, "float8_e4m3fn") and storage_dtype == jnp.float8_e4m3fn:
        return "fp8"
    raise ValueError(f"not a quantized KV storage dtype: {storage_dtype}")


def resolve_kv_quant(name=None):
    """Normalize a kv_dtype knob (arg or PADDLE_TPU_KV_DTYPE env) to a
    quant mode or None (= full-precision cache, the default)."""
    import os
    if name is None:
        name = os.environ.get("PADDLE_TPU_KV_DTYPE", "")
    name = str(name).strip().lower()
    if name in ("", "0", "none", "off", "dense", "fp32", "bf16",
                "bfloat16", "float32"):
        return None
    _check_mode(name)
    return name


def _cast_q(x_scaled, dtype: str):
    """Scaled values -> storage dtype (round+clip for int8, cast for
    fp8 — the f8 cast saturates/rounds in hardware convention)."""
    if dtype == "int8":
        return jnp.clip(jnp.round(x_scaled), -127.0, 127.0) \
            .astype(jnp.int8)
    return x_scaled.astype(jnp.float8_e4m3fn)


def quantize_channel(x, axis: int, dtype: str = "int8"):
    """Symmetric amax quantization per channel along ``axis`` (which is
    the axis REDUCED per channel — the contracting dim for a matmul
    operand).  Returns ``(q, scale)`` with ``scale`` keepdims-shaped so
    ``q.astype(f32) * scale ≈ x``."""
    _check_mode(dtype)
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / _qmax(dtype)
    return _cast_q(xf / scale, dtype), scale


def quantize_kv(x, dtype: str = "int8"):
    """KV-cache quantization at per-(position, head) granularity:
    ``x [..., head_dim]`` -> ``(q [..., head_dim], scale [...])`` with
    ``q.astype(f32) * scale[..., None] ≈ x``.  One f32 scale per
    head_dim values — a 1/64..1/128 metadata overhead next to the 2×
    byte saving on the values themselves."""
    q, scale = quantize_channel(x, axis=-1, dtype=dtype)
    return q, scale[..., 0]


def dequantize_kv(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv` (``scale`` without the trailing
    head_dim axis)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)


# ---------------------------------------------------------------------------
# composite (the CPU parity oracle)
# ---------------------------------------------------------------------------
def _qmm_composite(qx, qw, sx, sw, out_dtype):
    """(q_x · q_w) · s_x · s_w via one XLA dot_general.  int8 inputs
    accumulate in int32 (exact), fp8 in f32 (preferred_element_type)."""
    if qx.dtype == jnp.int8:
        acc = jax.lax.dot_general(
            qx, qw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32).astype(jnp.float32)
    else:
        acc = jax.lax.dot_general(
            qx, qw, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return (acc * sx * sw).astype(out_dtype)


# ---------------------------------------------------------------------------
# Pallas kernel: int8 MXU dots, int32 accumulation, f32 rescale
# ---------------------------------------------------------------------------
def quantized_matmul_available() -> bool:
    if not _fa._HAS_PLTPU:
        return False
    if _fa._INTERPRET:
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _qmm_kernel(x_ref, w_ref, sx_ref, sw_ref, o_ref, *, block_k: int):
    """One (m_block, n_block) program: x_ref [bm, K] int8 row strip,
    w_ref [K, bn] int8 column strip, sx (bm, 1) / sw (1, bn) f32
    per-channel scales; o_ref [bm, bn]."""
    k = x_ref.shape[1]
    n_k = k // block_k
    bm, bn = o_ref.shape

    def body(j, acc):
        x_blk = x_ref[:, pl.ds(j * block_k, block_k)]
        w_blk = w_ref[pl.ds(j * block_k, block_k), :]
        return acc + jax.lax.dot_general(
            x_blk, w_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    acc = jax.lax.fori_loop(0, n_k, body,
                            jnp.zeros((bm, bn), jnp.int32))
    o_ref[:] = (acc.astype(jnp.float32) * sx_ref[:] * sw_ref[:]) \
        .astype(o_ref.dtype)


def get_qmm_tiles(m: int, n: int, k: int, dtype: str = "int8"):
    """(block_m, block_n, block_k) for the quantized-matmul kernel:
    unified tuning table first (op "qmm_tiles", keyed by the shape
    bucket), then — with PADDLE_TPU_TUNING=sweep on a real TPU — a
    one-shot on-device sweep recorded back into the table, then
    defaults clamped to divide the problem.  The m key is bucketed to
    its power of two so one tuned entry serves every batch in its size
    class."""
    from ..utils import tuning as _tuning
    m_bucket = 1
    while m_bucket * 2 <= m:
        m_bucket *= 2
    key = (_tuning.device_kind(), m_bucket, n, k, dtype)
    tuned = _tuning.lookup("qmm_tiles", key)
    if tuned is None and dtype == "int8" and _tuning.sweep_enabled() \
            and not _fa._INTERPRET:
        try:
            import jax as _jax
            if _jax.default_backend() == "tpu":
                tuned = autotune_qmm_sweep(m_bucket, n, k)
        except Exception:   # sweep is best-effort; fall through
            tuned = None
    if tuned is None:
        # nearest tabled shape for the same (device, dtype) — a sweep
        # at one (m, n, k) should serve its size class, not leave every
        # off-by-a-bucket shape on hard defaults (the flash autotuner's
        # nearest-seq behaviour); _pick_block clamps whatever comes
        # back, so a mismatched entry can never yield an invalid grid
        tuned = _tuning.lookup_nearest("qmm_tiles", key,
                                       match_idx=(0, 4),
                                       near_idx=(1, 2, 3))
    if tuned is not None:
        try:
            bm, bn, bk = (int(tuned[0]), int(tuned[1]), int(tuned[2]))
            return (_fa._pick_block(m, bm), _fa._pick_block(n, bn),
                    _fa._pick_block(k, bk))
        except (ValueError, TypeError, IndexError):
            pass
    # defaults sized for the MXU: [bm, K]+[K, bn] int8 strips + the
    # [bm, bn] int32 accumulator stay well under VMEM at K ≤ 8192
    return (_fa._pick_block(m, 256), _fa._pick_block(n, 256),
            _fa._pick_block(k, 512))


def _qmm_pallas(qx, qw, sx, sw, out_dtype, dtype, tiles=None):
    m, k = qx.shape
    n = qw.shape[1]
    bm, bn, bk = tiles or get_qmm_tiles(m, n, k, dtype)
    kernel = functools.partial(_qmm_kernel, block_k=bk)
    return pl.pallas_call(
        kernel,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((bm, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=_fa._INTERPRET,
    )(qx, qw, sx, sw)


def _qmm_forward(x, w, dtype, out_dtype):
    """Shared quantize + dispatch body of quantized_matmul and the
    fake-quant forward: returns ``(y [..., N], qx, sx, qw, sw)`` with
    qx/sx over the flattened ``[M, K]`` activations.  ONE home for the
    kernel-gating predicate (m % 32: int8's native sublane tile —
    single-token decode matmuls take the composite, where they are
    noise anyway)."""
    _check_mode(dtype)
    lead = x.shape[:-1]
    k = x.shape[-1]
    n = w.shape[-1]
    x2 = x.reshape(-1, k)
    m = x2.shape[0]
    qx, sx = quantize_channel(x2, axis=1, dtype=dtype)     # sx [M, 1]
    qw, sw = quantize_channel(w, axis=0, dtype=dtype)      # sw [1, N]
    supported = (dtype == "int8" and m % 32 == 0 and n % 128 == 0
                 and k % 128 == 0)
    if supported and quantized_matmul_available():
        y = _qmm_pallas(qx, qw, sx, sw, out_dtype, dtype)
    else:
        y = _qmm_composite(qx, qw, sx, sw, out_dtype)
    return y.reshape(*lead, n), qx, sx, qw, sw


def quantized_matmul(x, w, dtype: str = "int8", out_dtype=None):
    """``x [..., K] @ w [K, N]`` through ``dtype`` quantization:
    activations amax-scaled per row, weights per output column, the
    8-bit dot rescaled back to ``out_dtype`` (default ``x.dtype``).
    Pallas kernel when shapes/backend allow, XLA composite otherwise —
    the composite is the parity oracle the kernel is tested against."""
    y, *_ = _qmm_forward(x, w, dtype, out_dtype or x.dtype)
    return y


def autotune_qmm_sweep(m: int, n: int, k: int, iters: int = 5):
    """One-shot on-device sweep over candidate int8 tiles for this
    shape; the winner lands in the unified tuning table (op
    "qmm_tiles") so every later process skips the sweep.  TPU only —
    interpret-mode timings are meaningless."""
    import time

    import numpy as np

    from ..utils import tuning as _tuning
    key = (_tuning.device_kind(), m, n, k, "int8")
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, k).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(k, n).astype(np.float32) * 0.1)
    qx, sx = quantize_channel(x, axis=1)
    qw, sw = quantize_channel(w, axis=0)

    best, best_t = None, None
    for bm in (64, 128, 256, 512):
        for bn in (128, 256, 512):
            for bk in (128, 256, 512, 1024):
                if m % bm or n % bn or k % bk or bm > m or bn > n \
                        or bk > k:
                    continue
                # int8 x/w strips + the int32 accumulator must fit VMEM
                if bm * k + k * bn + 4 * bm * bn > 12 * 2**20:
                    continue
                try:
                    fn = jax.jit(functools.partial(
                        _qmm_pallas, out_dtype=jnp.float32,
                        dtype="int8", tiles=(bm, bn, bk)))
                    jax.block_until_ready(fn(qx, qw, sx, sw))
                    t0 = time.perf_counter()
                    for _ in range(iters):
                        out = fn(qx, qw, sx, sw)
                    jax.block_until_ready(out)
                    t = (time.perf_counter() - t0) / iters
                except Exception:
                    continue            # tile rejected by the compiler
                if best_t is None or t < best_t:
                    best, best_t = (bm, bn, bk), t
    if best is not None:
        _tuning.record("qmm_tiles", key, list(best))
    return best


# ---------------------------------------------------------------------------
# fake-quant training op (straight-through estimator)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant_matmul(x, w, dtype: str = "int8"):
    """Quantized forward, straight-through backward.  Numerically equal
    to ``fq(x) @ fq(w)`` with ``fq(t) = t + sg(qdq(t) - t)`` — the
    model sees (and learns under) quantization noise while grads flow
    as if the matmul were full precision over the dequantized operands.
    The parameters stay fp32/bf16, so optimizers are untouched."""
    y, _ = _fake_quant_fwd(x, w, dtype)
    return y


def _fake_quant_fwd(x, w, dtype):
    y, qx, sx, qw, sw = _qmm_forward(x, w, dtype, x.dtype)
    # residuals: the DEQUANTIZED operands in the inputs' shapes/dtypes
    # (exactly fq(x)/fq(w) of the STE reference — residual leaves must
    # be arrays, so shape/dtype bookkeeping rides on them)
    xdq = (qx.astype(jnp.float32) * sx).reshape(x.shape).astype(x.dtype)
    wdq = (qw.astype(jnp.float32) * sw).astype(w.dtype)
    return y, (xdq, wdq)


def _fake_quant_bwd(dtype, res, g):
    xdq, wdq = res
    k = xdq.shape[-1]
    n = g.shape[-1]
    g2 = g.reshape(-1, n).astype(jnp.float32)
    x2 = xdq.reshape(-1, k).astype(jnp.float32)
    # STE: d/dx [fq(x) @ fq(w)] = g @ fq(w)^T, d/dw = fq(x)^T @ g —
    # quantization treated as identity in the backward pass
    dx = jax.lax.dot_general(g2, wdq.astype(jnp.float32),
                             (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    dw = jax.lax.dot_general(x2, g2, (((0,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    return dx.reshape(xdq.shape).astype(xdq.dtype), dw.astype(wdq.dtype)


fake_quant_matmul.defvjp(_fake_quant_fwd, _fake_quant_bwd)
