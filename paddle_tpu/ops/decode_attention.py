"""Pallas TPU fused single-token decode attention over a static KV cache.

The serving hot loop (inference.engine) appends ONE token per slot per
step and attends it against a preallocated, fixed-capacity cache
``[batch_slots, max_seq, kv_heads, head_dim]`` whose per-slot occupancy
is a ``lengths`` vector.  Decode attention is memory-bound — the whole
cost is streaming the KV cache through the chip once — so the fusion
target is different from training flash attention: there is no softmax
tiling problem (one query row), the win is reading each K/V block from
HBM exactly once and never materializing the [B, H, S] score matrix or
a repeat_interleaved K/V for GQA.

Kernel shape: grid ``(B·Hkv,)``; each program holds the slot's query
group ``[G, D]`` (G = H/Hkv query heads sharing one KV head) in VMEM and
streams the slot's ``[S, D]`` K/V strips block by block with a running
online-softmax max/denominator, masking key positions ``>= lengths[b]``.
Like ``flash_attention.py`` the mask rides in as an f32 ``[B, 1, S]``
strip (1 = valid) — trivially cheap next to the cache itself and it
keeps the kernel free of SMEM scalar plumbing.

The XLA composite (`_decode_composite`) is the CPU/fallback path and the
ground truth for the kernel tests; both use f32 score accumulation.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import importlib

# the package __init__ rebinds the name `flash_attention` to the public
# FUNCTION; fetch the sibling module itself (its _INTERPRET flag is
# mutable state we must read live)
_fa = importlib.import_module(__package__ + ".flash_attention")

__all__ = ["decode_attention", "decode_attention_available",
           "set_interpret_mode"]

_NEG = -1e30
_STATE = {"interpret": None}  # None = follow flash_attention's flag


def set_interpret_mode(flag):
    """True/False force interpret mode; None follows
    flash_attention.set_interpret_mode (so one test switch drives both
    kernels)."""
    _STATE["interpret"] = flag


def _interpret() -> bool:
    if _STATE["interpret"] is not None:
        return bool(_STATE["interpret"])
    return _fa._INTERPRET


def decode_attention_available() -> bool:
    if not _fa._HAS_PLTPU:
        return False
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _decode_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, block_k: int,
                   scale: float):
    """One (b·hkv) program: q_ref [G, D] query group; k/v [S, D] cache
    strips; m_ref (1, S) f32 validity; o_ref [G, D]."""
    g, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    # storage-dtype (bf16) MXU inputs, f32 accumulation — the same mixed
    # scheme as the training flash kernel
    q = q_ref[:]

    m0 = jnp.full((g, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [g, bk] f32
        kv_f = m_ref[0, pl.ds(j * block_k, block_k)]        # (bk,) f32
        sblk = jnp.where(kv_f[None, :] > 0, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)  # fully-masked blocks
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _decode_gqa(q3, k3, v3, mask, block_k=512):
    """q3 [B·Hkv, G, D]; k3/v3 [B·Hkv, S, D]; mask [B, 1, S] f32."""
    bhkv, g, d = q3.shape
    s = k3.shape[1]
    hkv = bhkv // mask.shape[0]
    block_k = _fa._pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bhkv,),
        in_specs=[
            pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, 1, s),
                         lambda b, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bhkv, g, d), q3.dtype),
        interpret=_interpret(),
    )(q3, k3, v3, mask)


def _decode_composite(q, k_cache, v_cache, lengths):
    """XLA reference math. q [B, H, D]; caches [B, S, Hkv, D]; lengths
    [B] int32 (valid tokens per slot, INCLUDING the one just written)."""
    b, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    g = h // hkv
    qg = q.reshape(b, hkv, g, d)
    kh = jnp.swapaxes(k_cache, 1, 2)                 # [b, hkv, s, d]
    vh = jnp.swapaxes(v_cache, 1, 2)
    scores = jnp.einsum("bkgd,bksd->bkgs", qg, kh,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    valid = jnp.arange(s)[None, None, None, :] < \
        lengths.astype(jnp.int32)[:, None, None, None]
    scores = jnp.where(valid, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, vh)
    return out.reshape(b, h, d).astype(q.dtype)


def decode_attention(q, k_cache, v_cache, lengths):
    """Single-token attention over a static, length-masked KV cache.

    q ``[B, H, D]`` — the new token's query per slot; k_cache/v_cache
    ``[B, S, Hkv, D]`` — fixed-capacity cache AFTER the new token's k/v
    were written; lengths ``[B]`` int32 — valid tokens per slot
    (including the new one).  Returns ``[B, H, D]``.  GQA is native
    (H % Hkv == 0, grouped ``h = hk·G + g`` like flash_attention).
    Pallas fused kernel when shapes allow, XLA composite otherwise.
    """
    b, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    supported = (s % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0)
    if not supported or not decode_attention_available():
        return _decode_composite(q, k_cache, v_cache, lengths)
    mask = (jnp.arange(s)[None, :] <
            lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    q3 = q.reshape(b, hkv, h // hkv, d).reshape(b * hkv, h // hkv, d)
    k3 = jnp.swapaxes(k_cache, 1, 2).reshape(b * hkv, s, d)
    v3 = jnp.swapaxes(v_cache, 1, 2).reshape(b * hkv, s, d)
    o3 = _decode_gqa(q3, k3, v3, mask.reshape(b, 1, s))
    return o3.reshape(b, hkv, h // hkv, d).reshape(b, h, d)
