"""Pallas TPU fused single-token decode attention over a static KV cache.

The serving hot loop (inference.engine) appends ONE token per slot per
step and attends it against a preallocated, fixed-capacity cache
``[batch_slots, max_seq, kv_heads, head_dim]`` whose per-slot occupancy
is a ``lengths`` vector.  Decode attention is memory-bound — the whole
cost is streaming the KV cache through the chip once — so the fusion
target is different from training flash attention: there is no softmax
tiling problem (one query row), the win is reading each K/V block from
HBM exactly once and never materializing the [B, H, S] score matrix or
a repeat_interleaved K/V for GQA.

Kernel shape: grid ``(B·Hkv,)``; each program holds the slot's query
group ``[G, D]`` (G = H/Hkv query heads sharing one KV head) in VMEM and
streams the slot's ``[S, D]`` K/V strips block by block with a running
online-softmax max/denominator, masking key positions ``>= lengths[b]``.
Like ``flash_attention.py`` the mask rides in as an f32 ``[B, 1, S]``
strip (1 = valid) — trivially cheap next to the cache itself and it
keeps the kernel free of SMEM scalar plumbing.

The XLA composite (`_decode_composite`) is the CPU/fallback path and the
ground truth for the kernel tests; both use f32 score accumulation.

Quantized KV (``kv_dtype='int8'`` in the caches): both entry points
accept optional per-(position, head) ``k_scale``/``v_scale`` arrays
(``[B, S, Hkv]`` dense / ``[num_blocks, block_size, Hkv]`` paged, f32;
see ops.quantized_matmul.quantize_kv).  The kernels stream the int8
values + f32 scales and dequantize INSIDE the block loop, so the bytes
leaving HBM per decode step halve (decode attention is bandwidth-bound
— that is the whole win); the composites dequantize up front and reuse
the dense math, which makes them the parity oracle against the fp
cache at quantization tolerance.

The window entry points (``decode_attention_window`` /
``paged_decode_attention_window``) are general over the window width W
and serve TWO schedulers: speculative-decode verify (W = draft K + 1)
and CHUNKED PREFILL (W = the chunk size) — the Sarathi-style admission
mode where each tick advances every still-prefilling slot by up to
`chunk` prompt tokens alongside the decode batch.  Both uses scatter
the window's k/v first and rely on the same staircase mask (query i
sees cache position j iff ``j <= lengths[b]+i``), so chunked prefill
needs no new kernels; the ``chunk_prefill_attention`` aliases at the
bottom of this module name that second contract explicitly and the
chunk tests pin it against the composites.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

import importlib

# the package __init__ rebinds the name `flash_attention` to the public
# FUNCTION; fetch the sibling module itself (its _INTERPRET flag is
# mutable state we must read live)
_fa = importlib.import_module(__package__ + ".flash_attention")

__all__ = ["decode_attention", "decode_attention_available",
           "paged_decode_attention", "paged_decode_attention_available",
           "decode_attention_window", "paged_decode_attention_window",
           "chunk_prefill_attention", "paged_chunk_prefill_attention",
           "set_interpret_mode"]

_NEG = -1e30
_STATE = {"interpret": None}  # None = follow flash_attention's flag


def set_interpret_mode(flag):
    """True/False force interpret mode; None follows
    flash_attention.set_interpret_mode (so one test switch drives both
    kernels)."""
    _STATE["interpret"] = flag


def _interpret() -> bool:
    if _STATE["interpret"] is not None:
        return bool(_STATE["interpret"])
    return _fa._INTERPRET


def decode_attention_available() -> bool:
    if not _fa._HAS_PLTPU:
        return False
    if _interpret():
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


def _tp_mesh(hkv: int, h: int):
    """The active serving/compile mesh when its 'tp' axis can partition
    these heads, else (None, 1).  The Pallas calls below are custom
    calls GSPMD cannot partition — under a tp-sharded serving engine
    (ISSUE 18) the entry points wrap them in shard_map over 'tp' with
    per-shard head ranges instead, so each device streams only its own
    KV-head slice (no collectives: decode attention is per-head).  The
    axis name matches the serving engines' create_mesh({'dp','tp'})
    convention (GPTConfig.tp_axis default)."""
    try:
        from ..distributed.mesh import get_mesh
        mesh = get_mesh()
    except Exception:  # pragma: no cover - circular-import safety
        return None, 1
    if mesh is None or "tp" not in mesh.axis_names:
        return None, 1
    tp = int(mesh.shape["tp"])
    if tp <= 1 or hkv % tp or h % tp:
        return None, 1
    return mesh, tp


def _shard_over_tp(body, mesh, in_specs, out_spec, args):
    """shard_map `body` over the mesh with the given per-operand
    PartitionSpecs (axes a spec does not name stay replicated)."""
    from ..distributed.mesh import shard_map
    return shard_map(body, mesh=mesh, in_specs=tuple(in_specs),
                     out_specs=out_spec, check_vma=False)(*args)


def _decode_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, block_k: int,
                   scale: float):
    """One (b·hkv) program: q_ref [G, D] query group; k/v [S, D] cache
    strips; m_ref (1, S) f32 validity; o_ref [G, D]."""
    g, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    # storage-dtype (bf16) MXU inputs, f32 accumulation — the same mixed
    # scheme as the training flash kernel
    q = q_ref[:]

    m0 = jnp.full((g, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [g, bk] f32
        kv_f = m_ref[0, pl.ds(j * block_k, block_k)]        # (bk,) f32
        sblk = jnp.where(kv_f[None, :] > 0, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)  # fully-masked blocks
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _decode_gqa(q3, k3, v3, mask, block_k=512):
    """q3 [B·Hkv, G, D]; k3/v3 [B·Hkv, S, D]; mask [B, 1, S] f32."""
    bhkv, g, d = q3.shape
    s = k3.shape[1]
    hkv = bhkv // mask.shape[0]
    block_k = _fa._pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_decode_kernel, block_k=block_k,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bhkv,),
        in_specs=[
            pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, 1, s),
                         lambda b, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bhkv, g, d), q3.dtype),
        interpret=_interpret(),
    )(q3, k3, v3, mask)


def _decode_kernel_q(q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref, o_ref,
                     *, block_k: int, scale: float):
    """Quantized-cache variant of _decode_kernel: k/v strips arrive in
    int8 with per-position f32 scale strips ((1, S), like the mask) and
    are dequantized block-by-block AFTER leaving HBM — the strips
    stream at half the bytes, which is the whole point of the int8
    cache on a bandwidth-bound kernel."""
    g, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    q = q_ref[:]
    m0 = jnp.full((g, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((g, 1), jnp.float32)
    acc0 = jnp.zeros((g, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        ks = ks_ref[0, pl.ds(j * block_k, block_k)]         # (bk,) f32
        vs = vs_ref[0, pl.ds(j * block_k, block_k)]
        k_blk = (k_ref[pl.ds(j * block_k, block_k), :]
                 .astype(jnp.float32) * ks[:, None]).astype(q.dtype)
        v_blk = (v_ref[pl.ds(j * block_k, block_k), :]
                 .astype(jnp.float32) * vs[:, None]).astype(q.dtype)
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale     # [g, bk] f32
        kv_f = m_ref[0, pl.ds(j * block_k, block_k)]        # (bk,) f32
        sblk = jnp.where(kv_f[None, :] > 0, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)  # fully-masked blocks
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _decode_gqa_q(q3, k3, v3, ks3, vs3, mask, block_k=512):
    """Quantized wrapper: q3 [B·Hkv, G, D]; k3/v3 [B·Hkv, S, D] int8;
    ks3/vs3 [B·Hkv, 1, S] f32 scales; mask [B, 1, S] f32."""
    bhkv, g, d = q3.shape
    s = k3.shape[1]
    hkv = bhkv // mask.shape[0]
    block_k = _fa._pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    kernel = functools.partial(_decode_kernel_q, block_k=block_k,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid=(bhkv,),
        in_specs=[
            pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, s, d), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, 1, s), lambda b: (b, 0, 0)),
            pl.BlockSpec((None, 1, s),
                         lambda b, hkv=hkv: (b // hkv, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, g, d), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((bhkv, g, d), q3.dtype),
        interpret=_interpret(),
    )(q3, k3, v3, ks3, vs3, mask)


def _dequant_cache(cache, scale, dtype):
    """int8/f8 cache values [..., Hkv, D] × per-(position, head) scales
    [..., Hkv] -> compute dtype."""
    return (cache.astype(jnp.float32) *
            scale[..., None].astype(jnp.float32)).astype(dtype)


def _decode_composite(q, k_cache, v_cache, lengths, k_scale=None,
                      v_scale=None):
    """XLA reference math. q [B, H, D]; caches [B, S, Hkv, D]; lengths
    [B] int32 (valid tokens per slot, INCLUDING the one just written).
    With ``k_scale``/``v_scale`` ([B, S, Hkv] f32) the caches hold
    quantized values: dequantize up front, then the IDENTICAL dense
    math — bitwise the dense composite on the dequantized contents."""
    if k_scale is not None:
        k_cache = _dequant_cache(k_cache, k_scale, q.dtype)
        v_cache = _dequant_cache(v_cache, v_scale, q.dtype)
    b, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    g = h // hkv
    qg = q.reshape(b, hkv, g, d)
    kh = jnp.swapaxes(k_cache, 1, 2)                 # [b, hkv, s, d]
    vh = jnp.swapaxes(v_cache, 1, 2)
    scores = jnp.einsum("bkgd,bksd->bkgs", qg, kh,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    valid = jnp.arange(s)[None, None, None, :] < \
        lengths.astype(jnp.int32)[:, None, None, None]
    scores = jnp.where(valid, scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, vh)
    return out.reshape(b, h, d).astype(q.dtype)


def decode_attention(q, k_cache, v_cache, lengths, k_scale=None,
                     v_scale=None):
    """Single-token attention over a static, length-masked KV cache.

    q ``[B, H, D]`` — the new token's query per slot; k_cache/v_cache
    ``[B, S, Hkv, D]`` — fixed-capacity cache AFTER the new token's k/v
    were written; lengths ``[B]`` int32 — valid tokens per slot
    (including the new one).  With a quantized cache, ``k_scale``/
    ``v_scale`` carry the per-(position, head) f32 scales
    (``[B, S, Hkv]``) and the cache values are int8 (fp8 rides the
    composite).  Returns ``[B, H, D]``.  GQA is native (H % Hkv == 0,
    grouped ``h = hk·G + g`` like flash_attention).  Pallas fused
    kernel when shapes allow, XLA composite otherwise.
    """
    b, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    quantized = k_scale is not None
    supported = (s % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0
                 and (not quantized or k_cache.dtype == jnp.int8))
    if not supported or not decode_attention_available():
        return _decode_composite(q, k_cache, v_cache, lengths,
                                 k_scale, v_scale)
    mesh, _tp = _tp_mesh(hkv, h)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        specs = [P(None, "tp", None), P(None, None, "tp", None),
                 P(None, None, "tp", None), P(None)]
        args = [q, k_cache, v_cache, lengths]
        if quantized:
            specs += [P(None, None, "tp"), P(None, None, "tp")]
            args += [k_scale, v_scale]
        return _shard_over_tp(_decode_kernel_path, mesh, specs,
                              P(None, "tp", None), args)
    return _decode_kernel_path(q, k_cache, v_cache, lengths, k_scale,
                               v_scale)


def _decode_kernel_path(q, k_cache, v_cache, lengths, k_scale=None,
                        v_scale=None):
    """The dense kernel dispatch AFTER the support gate — also the
    shard_map body under tp (per-shard head ranges, same code)."""
    b, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    mask = (jnp.arange(s)[None, :] <
            lengths.astype(jnp.int32)[:, None]).astype(jnp.float32)
    q3 = q.reshape(b, hkv, h // hkv, d).reshape(b * hkv, h // hkv, d)
    k3 = jnp.swapaxes(k_cache, 1, 2).reshape(b * hkv, s, d)
    v3 = jnp.swapaxes(v_cache, 1, 2).reshape(b * hkv, s, d)
    if k_scale is not None:
        ks3 = jnp.swapaxes(k_scale.astype(jnp.float32), 1, 2) \
            .reshape(b * hkv, 1, s)
        vs3 = jnp.swapaxes(v_scale.astype(jnp.float32), 1, 2) \
            .reshape(b * hkv, 1, s)
        o3 = _decode_gqa_q(q3, k3, v3, ks3, vs3, mask.reshape(b, 1, s))
    else:
        o3 = _decode_gqa(q3, k3, v3, mask.reshape(b, 1, s))
    return o3.reshape(b, hkv, h // hkv, d).reshape(b, h, d)


# ---------------------------------------------------------------------------
# paged variant: K/V live in a block pool, streamed through a block table
# ---------------------------------------------------------------------------
def paged_decode_attention_available() -> bool:
    """The paged kernel additionally needs scalar prefetch (the block
    table drives the K/V DMA addresses), so it requires the pltpu grid
    spec — same availability surface as the dense kernel otherwise."""
    return decode_attention_available() and _fa.pltpu is not None


def _paged_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, block_size: int, hkv: int,
                  scale: float):
    """One (b·hkv, j) program: j walks the slot's block table; the
    BlockSpec index_map already resolved table entry j to a pool block,
    so k_ref/v_ref hold that block's ``[block_size, D]`` strip for this
    kv head.  Online-softmax state (m/l/acc) persists in VMEM scratch
    across the j steps (TPU grids run sequentially, innermost fastest);
    the output is written once on the last block."""
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    b = pl.program_id(0) // hkv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[:]                                        # [G, D]
    k_blk = k_ref[:]                                    # [bs, D]
    v_blk = v_ref[:]
    sblk = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale     # [G, bs] f32
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    sblk = jnp.where(pos < len_ref[b], sblk, _NEG)
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
    p = jnp.exp(sblk - m_new)
    p = jnp.where(sblk <= _NEG / 2, 0.0, p)             # fully-masked blocks
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_blocks - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _paged_gqa(q3, k_pool, v_pool, tables, lengths):
    """q3 [B·Hkv, G, D]; pools [NB, bs, Hkv, D]; tables [B, MB] int32;
    lengths [B] int32.  Scalar-prefetched tables/lengths let each grid
    step's index_map pick its pool block, so only the slot's own blocks
    ever leave HBM (no gather of the whole table into dense form)."""
    pltpu = _fa.pltpu
    bhkv, g, d = q3.shape
    bs = k_pool.shape[1]
    b, mb = tables.shape
    hkv = bhkv // b
    scale = 1.0 / math.sqrt(d)
    kv_spec = pl.BlockSpec(
        (None, bs, None, d),
        lambda i, j, tbl, lens, hkv=hkv: (tbl[i // hkv, j], 0, i % hkv, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bhkv, mb),
        in_specs=[
            pl.BlockSpec((None, g, d), lambda i, j, tbl, lens: (i, 0, 0)),
            kv_spec, kv_spec,
        ],
        out_specs=pl.BlockSpec((None, g, d),
                               lambda i, j, tbl, lens: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 128), jnp.float32),   # running max
            pltpu.VMEM((g, 128), jnp.float32),   # running denominator
            pltpu.VMEM((g, d), jnp.float32),     # output accumulator
        ],
    )
    kernel = functools.partial(_paged_kernel, block_size=bs, hkv=hkv,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, g, d), q3.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q3, k_pool, v_pool)


def _paged_composite(q, k_pool, v_pool, tables, lengths, k_scale=None,
                     v_scale=None):
    """XLA reference math: gather each slot's blocks into the dense
    ``[B, S, Hkv, D]`` layout (S = MB·bs) and reuse the dense composite.
    Bitwise-identical to the dense path on identical cache contents —
    the parity oracle tests/test_paged_kv.py leans on.  Quantized pools
    gather their ``[num_blocks, bs, Hkv]`` scale pools the same way."""
    b, mb = tables.shape
    bs, hkv, d = k_pool.shape[1], k_pool.shape[2], k_pool.shape[3]
    kg = k_pool[tables].reshape(b, mb * bs, hkv, d)
    vg = v_pool[tables].reshape(b, mb * bs, hkv, d)
    ksg = vsg = None
    if k_scale is not None:
        ksg = k_scale[tables].reshape(b, mb * bs, hkv)
        vsg = v_scale[tables].reshape(b, mb * bs, hkv)
    return _decode_composite(q, kg, vg, lengths, ksg, vsg)


def _paged_kernel_q(tbl_ref, len_ref, q_ref, k_ref, v_ref, ks_ref,
                    vs_ref, o_ref, m_scr, l_scr, acc_scr, *,
                    block_size: int, hkv: int, scale: float):
    """Quantized-pool variant of _paged_kernel: the BlockSpec index_map
    resolved table entry j to a pool block for the int8 values AND the
    f32 scale strip ([1, bs], from the [NB, Hkv, bs]-transposed scale
    pools); dequantize after the DMA, then the same online softmax."""
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    b = pl.program_id(0) // hkv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[:]                                        # [G, D]
    ks = ks_ref[0, :]                                   # (bs,) f32
    vs = vs_ref[0, :]
    k_blk = (k_ref[:].astype(jnp.float32) * ks[:, None]).astype(q.dtype)
    v_blk = (v_ref[:].astype(jnp.float32) * vs[:, None]).astype(q.dtype)
    sblk = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale     # [G, bs] f32
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    sblk = jnp.where(pos < len_ref[b], sblk, _NEG)
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
    p = jnp.exp(sblk - m_new)
    p = jnp.where(sblk <= _NEG / 2, 0.0, p)             # fully-masked blocks
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_blocks - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _paged_gqa_q(q3, k_pool, v_pool, k_scale, v_scale, tables, lengths):
    """Quantized paged wrapper: value pools int8 [NB, bs, Hkv, D],
    scale pools [NB, bs, Hkv] f32 (transposed here to [NB, Hkv, bs] so
    each grid step's scale block is a 2-D [1, bs] strip)."""
    pltpu = _fa.pltpu
    bhkv, g, d = q3.shape
    bs = k_pool.shape[1]
    b, mb = tables.shape
    hkv = bhkv // b
    scale = 1.0 / math.sqrt(d)
    ks_t = jnp.swapaxes(k_scale.astype(jnp.float32), 1, 2)
    vs_t = jnp.swapaxes(v_scale.astype(jnp.float32), 1, 2)
    kv_spec = pl.BlockSpec(
        (None, bs, None, d),
        lambda i, j, tbl, lens, hkv=hkv: (tbl[i // hkv, j], 0, i % hkv, 0))
    sc_spec = pl.BlockSpec(
        (None, 1, bs),
        lambda i, j, tbl, lens, hkv=hkv: (tbl[i // hkv, j], i % hkv, 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bhkv, mb),
        in_specs=[
            pl.BlockSpec((None, g, d), lambda i, j, tbl, lens: (i, 0, 0)),
            kv_spec, kv_spec, sc_spec, sc_spec,
        ],
        out_specs=pl.BlockSpec((None, g, d),
                               lambda i, j, tbl, lens: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((g, 128), jnp.float32),   # running max
            pltpu.VMEM((g, 128), jnp.float32),   # running denominator
            pltpu.VMEM((g, d), jnp.float32),     # output accumulator
        ],
    )
    kernel = functools.partial(_paged_kernel_q, block_size=bs, hkv=hkv,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, g, d), q3.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32),
      q3, k_pool, v_pool, ks_t, vs_t)


def paged_decode_attention(q, k_pool, v_pool, tables, lengths,
                           k_scale=None, v_scale=None):
    """Single-token attention over a PAGED, length-masked KV cache.

    q ``[B, H, D]`` — the new token's query per slot; k_pool/v_pool
    ``[num_blocks, block_size, Hkv, D]`` — the shared block pool AFTER
    the new token's k/v were written; tables ``[B, max_blocks]`` int32 —
    per-slot block table (pool indices; entries past the slot's extent
    point at the reserved null block and stay masked); lengths ``[B]``
    int32 — valid tokens per slot including the new one.  With a
    quantized pool, ``k_scale``/``v_scale`` are the
    ``[num_blocks, block_size, Hkv]`` f32 scale pools and the value
    pools are int8 (fp8 rides the composite).  Returns ``[B, H, D]``.
    The Pallas kernel streams K/V (and scales) block-by-block through
    the block table via scalar prefetch; the XLA composite gathers the
    table into dense form and is the CPU/fallback ground truth.
    """
    b, h, d = q.shape
    bs, hkv = k_pool.shape[1], k_pool.shape[2]
    quantized = k_scale is not None
    supported = (bs % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0
                 and (not quantized or k_pool.dtype == jnp.int8))
    if not supported or not paged_decode_attention_available():
        return _paged_composite(q, k_pool, v_pool, tables, lengths,
                                k_scale, v_scale)
    mesh, _tp = _tp_mesh(hkv, h)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        specs = [P(None, "tp", None), P(None, None, "tp", None),
                 P(None, None, "tp", None), P(None, None), P(None)]
        args = [q, k_pool, v_pool, tables, lengths]
        if quantized:
            specs += [P(None, None, "tp"), P(None, None, "tp")]
            args += [k_scale, v_scale]
        return _shard_over_tp(_paged_kernel_path, mesh, specs,
                              P(None, "tp", None), args)
    return _paged_kernel_path(q, k_pool, v_pool, tables, lengths,
                              k_scale, v_scale)


def _paged_kernel_path(q, k_pool, v_pool, tables, lengths, k_scale=None,
                       v_scale=None):
    """The paged kernel dispatch AFTER the support gate — also the
    shard_map body under tp (block tables stay replicated: allocation
    is host state, each shard walks the same tables over its own
    head-slice of the pool)."""
    b, h, d = q.shape
    hkv = k_pool.shape[2]
    q3 = q.reshape(b, hkv, h // hkv, d).reshape(b * hkv, h // hkv, d)
    if k_scale is not None:
        o3 = _paged_gqa_q(q3, k_pool, v_pool, k_scale, v_scale, tables,
                          lengths)
    else:
        o3 = _paged_gqa(q3, k_pool, v_pool, tables, lengths)
    return o3.reshape(b, hkv, h // hkv, d).reshape(b, h, d)


# ---------------------------------------------------------------------------
# window variant: K+1 query tokens per slot in ONE call — the verify
# half of speculative decoding (Leviathan et al.).  The draft proposes K
# tokens; the target model scores all K+1 positions against the cache in
# one fixed-shape executable instead of K+1 sequential decode steps.
# Query i (absolute position lengths[b]+i) attends cache positions
# j <= lengths[b]+i, where `lengths` counts tokens cached BEFORE the
# window (the caller scatters the window's k/v at lengths..lengths+W-1
# first, exactly like the single-token write-then-attend order).
# ---------------------------------------------------------------------------
def _window_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, *, block_k: int,
                   g: int, scale: float):
    """One (b·hkv) program: q_ref [W·G, D] — W window queries × G query
    heads per kv head, rows grouped w·G+g; k/v [S, D] cache strips;
    m_ref (W, S) f32 per-QUERY validity (the staircase mask); o [W·G, D].
    Same online softmax as _decode_kernel with the mask row picked per
    query row."""
    wg, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    q = q_ref[:]
    m0 = jnp.full((wg, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((wg, 1), jnp.float32)
    acc0 = jnp.zeros((wg, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[pl.ds(j * block_k, block_k), :]
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [wg, bk] f32
        kv_f = m_ref[:, pl.ds(j * block_k, block_k)]       # (W, bk) f32
        kv_f = jnp.repeat(kv_f, g, axis=0)                 # (wg, bk)
        sblk = jnp.where(kv_f > 0, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _window_kernel_q(q_ref, k_ref, v_ref, ks_ref, vs_ref, m_ref, o_ref,
                     *, block_k: int, g: int, scale: float):
    """Quantized-cache window kernel: int8 strips + (1, S) f32 scale
    strips dequantized after the DMA (scales are per cache POSITION, so
    they are shared by every query row)."""
    wg, d = q_ref.shape
    s = k_ref.shape[0]
    n_k = s // block_k

    q = q_ref[:]
    m0 = jnp.full((wg, 1), _NEG, jnp.float32)
    l0 = jnp.zeros((wg, 1), jnp.float32)
    acc0 = jnp.zeros((wg, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        ks = ks_ref[0, pl.ds(j * block_k, block_k)]
        vs = vs_ref[0, pl.ds(j * block_k, block_k)]
        k_blk = (k_ref[pl.ds(j * block_k, block_k), :]
                 .astype(jnp.float32) * ks[:, None]).astype(q.dtype)
        v_blk = (v_ref[pl.ds(j * block_k, block_k), :]
                 .astype(jnp.float32) * vs[:, None]).astype(q.dtype)
        sblk = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        kv_f = jnp.repeat(m_ref[:, pl.ds(j * block_k, block_k)], g,
                          axis=0)
        sblk = jnp.where(kv_f > 0, sblk, _NEG)
        m_new = jnp.maximum(m, jnp.max(sblk, axis=1, keepdims=True))
        p = jnp.exp(sblk - m_new)
        p = jnp.where(sblk <= _NEG / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, n_k, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _window_gqa(q3, k3, v3, mask, ks3=None, vs3=None, block_k=512):
    """q3 [B·Hkv, W·G, D]; k3/v3 [B·Hkv, S, D]; mask [B, W, S] f32;
    quantized path adds ks3/vs3 [B·Hkv, 1, S] f32 scale strips."""
    bhkv, wg, d = q3.shape
    s = k3.shape[1]
    b, w = mask.shape[0], mask.shape[1]
    hkv = bhkv // b
    g = wg // w
    block_k = _fa._pick_block(s, block_k)
    scale = 1.0 / math.sqrt(d)
    mask_spec = pl.BlockSpec((None, w, s),
                             lambda i, hkv=hkv: (i // hkv, 0, 0))
    io_spec = pl.BlockSpec((None, wg, d), lambda i: (i, 0, 0))
    kv_spec = pl.BlockSpec((None, s, d), lambda i: (i, 0, 0))
    if ks3 is None:
        kernel = functools.partial(_window_kernel, block_k=block_k, g=g,
                                   scale=scale)
        in_specs = [io_spec, kv_spec, kv_spec, mask_spec]
        args = (q3, k3, v3, mask)
    else:
        kernel = functools.partial(_window_kernel_q, block_k=block_k,
                                   g=g, scale=scale)
        sc_spec = pl.BlockSpec((None, 1, s), lambda i: (i, 0, 0))
        in_specs = [io_spec, kv_spec, kv_spec, sc_spec, sc_spec,
                    mask_spec]
        args = (q3, k3, v3, ks3, vs3, mask)
    return pl.pallas_call(
        kernel,
        grid=(bhkv,),
        in_specs=in_specs,
        out_specs=io_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, wg, d), q3.dtype),
        interpret=_interpret(),
    )(*args)


def _window_composite(q, k_cache, v_cache, lengths, k_scale=None,
                      v_scale=None):
    """XLA reference math for the window variant. q [B, W, H, D];
    caches [B, S, Hkv, D]; lengths [B] int32 EXCLUDING the window
    (query i sees cache positions j <= lengths[b]+i)."""
    if k_scale is not None:
        k_cache = _dequant_cache(k_cache, k_scale, q.dtype)
        v_cache = _dequant_cache(v_cache, v_scale, q.dtype)
    b, w, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    g = h // hkv
    qg = q.reshape(b, w, hkv, g, d)
    kh = jnp.swapaxes(k_cache, 1, 2)                 # [b, hkv, s, d]
    vh = jnp.swapaxes(v_cache, 1, 2)
    scores = jnp.einsum("bwkgd,bksd->bkwgs", qg, kh,
                        preferred_element_type=jnp.float32) / math.sqrt(d)
    limit = lengths.astype(jnp.int32)[:, None] + \
        jnp.arange(w, dtype=jnp.int32)[None, :] + 1        # [b, w]
    valid = jnp.arange(s)[None, None, :] < limit[:, :, None]
    scores = jnp.where(valid[:, None, :, None, :], scores, _NEG)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkwgs,bksd->bwkgd", probs, vh)
    return out.reshape(b, w, h, d).astype(q.dtype)


def decode_attention_window(q, k_cache, v_cache, lengths, k_scale=None,
                            v_scale=None):
    """Windowed multi-token attention over a static KV cache — the
    spec-decode verify primitive.

    q ``[B, W, H, D]`` — W consecutive new tokens' queries per slot
    (W = draft K + 1 in the verify step); k_cache/v_cache
    ``[B, S, Hkv, D]`` AFTER the window's k/v were written at positions
    ``lengths..lengths+W-1``; lengths ``[B]`` int32 — tokens cached
    BEFORE the window.  Query i attends ``j <= lengths[b]+i`` (itself
    included), so logits[i] is exactly what a sequential decode of
    token i would produce — that equivalence is the token-identity
    guarantee speculative decoding rests on.  ``W=1`` reduces to
    ``decode_attention`` with lengths+1.  Quantized caches pass their
    ``[B, S, Hkv]`` f32 scale planes.  Returns ``[B, W, H, D]``."""
    b, w, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    quantized = k_scale is not None
    supported = (s % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0
                 and (not quantized or k_cache.dtype == jnp.int8))
    if not supported or not decode_attention_available():
        return _window_composite(q, k_cache, v_cache, lengths,
                                 k_scale, v_scale)
    mesh, _tp = _tp_mesh(hkv, h)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        specs = [P(None, None, "tp", None), P(None, None, "tp", None),
                 P(None, None, "tp", None), P(None)]
        args = [q, k_cache, v_cache, lengths]
        if quantized:
            specs += [P(None, None, "tp"), P(None, None, "tp")]
            args += [k_scale, v_scale]
        return _shard_over_tp(_window_kernel_path, mesh, specs,
                              P(None, None, "tp", None), args)
    return _window_kernel_path(q, k_cache, v_cache, lengths, k_scale,
                               v_scale)


def _window_kernel_path(q, k_cache, v_cache, lengths, k_scale=None,
                        v_scale=None):
    """The dense window-kernel dispatch AFTER the support gate — also
    the shard_map body under tp."""
    b, w, h, d = q.shape
    s, hkv = k_cache.shape[1], k_cache.shape[2]
    limit = lengths.astype(jnp.int32)[:, None] + \
        jnp.arange(w, dtype=jnp.int32)[None, :] + 1
    mask = (jnp.arange(s)[None, None, :] <
            limit[:, :, None]).astype(jnp.float32)          # [b, w, s]
    # rows grouped (w, g): [b, w, hkv, g, d] -> [b, hkv, w, g, d]
    q3 = q.reshape(b, w, hkv, h // hkv, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b * hkv, w * (h // hkv), d)
    k3 = jnp.swapaxes(k_cache, 1, 2).reshape(b * hkv, s, d)
    v3 = jnp.swapaxes(v_cache, 1, 2).reshape(b * hkv, s, d)
    ks3 = vs3 = None
    if k_scale is not None:
        ks3 = jnp.swapaxes(k_scale.astype(jnp.float32), 1, 2) \
            .reshape(b * hkv, 1, s)
        vs3 = jnp.swapaxes(v_scale.astype(jnp.float32), 1, 2) \
            .reshape(b * hkv, 1, s)
    o3 = _window_gqa(q3, k3, v3, mask, ks3, vs3)
    return o3.reshape(b, hkv, w, h // hkv, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, w, h, d)


def _paged_window_kernel(tbl_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                         m_scr, l_scr, acc_scr, *, block_size: int,
                         hkv: int, g: int, scale: float):
    """Paged window program (b·hkv, j): like _paged_kernel with W·G
    query rows and the staircase mask computed in-kernel — row r's
    window index is r//g, so position p is valid iff
    p < len_ref[b] + r//g + 1."""
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    b = pl.program_id(0) // hkv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[:]                                        # [W·G, D]
    wg = q.shape[0]
    k_blk = k_ref[:]
    v_blk = v_ref[:]
    sblk = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale     # [wg, bs] f32
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)                  # [1, bs]
    win = jax.lax.broadcasted_iota(jnp.int32, (wg, 1), 0) // g
    sblk = jnp.where(pos < len_ref[b] + win + 1, sblk, _NEG)
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
    p = jnp.exp(sblk - m_new)
    p = jnp.where(sblk <= _NEG / 2, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_blocks - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _paged_window_kernel_q(tbl_ref, len_ref, q_ref, k_ref, v_ref,
                           ks_ref, vs_ref, o_ref, m_scr, l_scr, acc_scr,
                           *, block_size: int, hkv: int, g: int,
                           scale: float):
    """Quantized paged window program: dequantize the int8 strip with
    its [1, bs] scale strip after the DMA, then _paged_window_kernel's
    math."""
    j = pl.program_id(1)
    n_blocks = pl.num_programs(1)
    b = pl.program_id(0) // hkv

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _NEG)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    q = q_ref[:]
    wg = q.shape[0]
    ks = ks_ref[0, :]
    vs = vs_ref[0, :]
    k_blk = (k_ref[:].astype(jnp.float32) * ks[:, None]).astype(q.dtype)
    v_blk = (v_ref[:].astype(jnp.float32) * vs[:, None]).astype(q.dtype)
    sblk = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    pos = j * block_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_size), 1)
    win = jax.lax.broadcasted_iota(jnp.int32, (wg, 1), 0) // g
    sblk = jnp.where(pos < len_ref[b] + win + 1, sblk, _NEG)
    m_prev = m_scr[:, :1]
    l_prev = l_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(sblk, axis=1, keepdims=True))
    p = jnp.exp(sblk - m_new)
    p = jnp.where(sblk <= _NEG / 2, 0.0, p)
    alpha = jnp.exp(m_prev - m_new)
    l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
    acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == n_blocks - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] /
                    jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


def _paged_window_gqa(q3, k_pool, v_pool, tables, lengths, w,
                      k_scale=None, v_scale=None):
    """q3 [B·Hkv, W·G, D]; pools/tables/lengths as _paged_gqa; scale
    pools transposed to [NB, Hkv, bs] strips when quantized."""
    pltpu = _fa.pltpu
    bhkv, wg, d = q3.shape
    bs = k_pool.shape[1]
    b, mb = tables.shape
    hkv = bhkv // b
    g = wg // w
    scale = 1.0 / math.sqrt(d)
    kv_spec = pl.BlockSpec(
        (None, bs, None, d),
        lambda i, j, tbl, lens, hkv=hkv: (tbl[i // hkv, j], 0, i % hkv, 0))
    io_spec = pl.BlockSpec((None, wg, d),
                           lambda i, j, tbl, lens: (i, 0, 0))
    scratch = [
        pltpu.VMEM((wg, 128), jnp.float32),
        pltpu.VMEM((wg, 128), jnp.float32),
        pltpu.VMEM((wg, d), jnp.float32),
    ]
    if k_scale is None:
        in_specs = [io_spec, kv_spec, kv_spec]
        kernel = functools.partial(_paged_window_kernel, block_size=bs,
                                   hkv=hkv, g=g, scale=scale)
        args = (q3, k_pool, v_pool)
    else:
        sc_spec = pl.BlockSpec(
            (None, 1, bs),
            lambda i, j, tbl, lens, hkv=hkv: (tbl[i // hkv, j],
                                              i % hkv, 0))
        in_specs = [io_spec, kv_spec, kv_spec, sc_spec, sc_spec]
        kernel = functools.partial(_paged_window_kernel_q, block_size=bs,
                                   hkv=hkv, g=g, scale=scale)
        args = (q3, k_pool, v_pool,
                jnp.swapaxes(k_scale.astype(jnp.float32), 1, 2),
                jnp.swapaxes(v_scale.astype(jnp.float32), 1, 2))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(bhkv, mb),
        in_specs=in_specs,
        out_specs=io_spec,
        scratch_shapes=scratch,
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bhkv, wg, d), q3.dtype),
        interpret=_interpret(),
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), *args)


def _paged_window_composite(q, k_pool, v_pool, tables, lengths,
                            k_scale=None, v_scale=None):
    """Gather the slot's blocks dense, reuse the dense window composite
    — bitwise the dense path on identical cache contents."""
    b, mb = tables.shape
    bs, hkv, d = k_pool.shape[1], k_pool.shape[2], k_pool.shape[3]
    kg = k_pool[tables].reshape(b, mb * bs, hkv, d)
    vg = v_pool[tables].reshape(b, mb * bs, hkv, d)
    ksg = vsg = None
    if k_scale is not None:
        ksg = k_scale[tables].reshape(b, mb * bs, hkv)
        vsg = v_scale[tables].reshape(b, mb * bs, hkv)
    return _window_composite(q, kg, vg, lengths, ksg, vsg)


def paged_decode_attention_window(q, k_pool, v_pool, tables, lengths,
                                  k_scale=None, v_scale=None):
    """Windowed multi-token attention over a PAGED KV cache — the
    spec-decode verify primitive for the paged layout.  q
    ``[B, W, H, D]``; pools/tables as :func:`paged_decode_attention`;
    lengths ``[B]`` int32 EXCLUDING the window (its k/v were already
    scattered through the block table at positions
    ``lengths..lengths+W-1``).  Query i attends ``j <= lengths[b]+i``.
    Pallas scalar-prefetch kernel when shapes allow, gather composite
    (ground truth) otherwise."""
    b, w, h, d = q.shape
    bs, hkv = k_pool.shape[1], k_pool.shape[2]
    quantized = k_scale is not None
    supported = (bs % 128 == 0 and (d % 128 == 0 or d == 64)
                 and h % hkv == 0
                 and (not quantized or k_pool.dtype == jnp.int8))
    if not supported or not paged_decode_attention_available():
        return _paged_window_composite(q, k_pool, v_pool, tables,
                                       lengths, k_scale, v_scale)
    mesh, _tp = _tp_mesh(hkv, h)
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        specs = [P(None, None, "tp", None), P(None, None, "tp", None),
                 P(None, None, "tp", None), P(None, None), P(None)]
        args = [q, k_pool, v_pool, tables, lengths]
        if quantized:
            specs += [P(None, None, "tp"), P(None, None, "tp")]
            args += [k_scale, v_scale]
        return _shard_over_tp(
            functools.partial(_paged_window_kernel_path, w=w), mesh,
            specs, P(None, None, "tp", None), args)
    return _paged_window_kernel_path(q, k_pool, v_pool, tables, lengths,
                                     k_scale, v_scale, w=w)


def _paged_window_kernel_path(q, k_pool, v_pool, tables, lengths,
                              k_scale=None, v_scale=None, *, w):
    """The paged window-kernel dispatch AFTER the support gate — also
    the shard_map body under tp (tables replicated; each shard walks
    the same tables over its own head-slice of the pool)."""
    b, _, h, d = q.shape
    hkv = k_pool.shape[2]
    q3 = q.reshape(b, w, hkv, h // hkv, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b * hkv, w * (h // hkv), d)
    o3 = _paged_window_gqa(q3, k_pool, v_pool, tables, lengths, w,
                           k_scale, v_scale)
    return o3.reshape(b, hkv, w, h // hkv, d).transpose(0, 2, 1, 3, 4) \
        .reshape(b, w, h, d)


# ---- chunked-prefill aliases -------------------------------------------
# Chunked prefill (ISSUE 20) IS the window attention with W = chunk:
# the engine scatters a [B, chunk] slice of each still-prefilling
# slot's prompt at positions lengths..lengths+chunk-1, and query i must
# see exactly j <= lengths[b]+i — the same staircase the spec verify
# needs.  The aliases give the chunk scheduler (and its tests) a name
# for that contract without duplicating a kernel; the support gate,
# tp shard_map path, int8 scale strips and composite oracles all come
# along for free.
chunk_prefill_attention = decode_attention_window
paged_chunk_prefill_attention = paged_decode_attention_window
