"""paddle.vision parity (reference python/paddle/vision/)."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import models  # noqa: F401
from . import ops  # noqa: F401
from .models import (  # noqa: F401
    LeNet, ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    VGG, vgg11, vgg13, vgg16, vgg19, MobileNetV1, MobileNetV2,
    mobilenet_v1, mobilenet_v2)
