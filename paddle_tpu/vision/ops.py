"""Detection op family — boxes, IoU, NMS, anchors, box coding.

Reference: /root/reference/paddle/fluid/operators/detection/
(bbox_util.h box math, iou_similarity_op.h, box_coder_op.h encode/
decode, nms in multiclass_nms_op.cc, prior_box_op.h anchors) and
python/paddle/fluid/layers/detection.py.

TPU-native shape: every op is fixed-shape, mask-based jnp code — NMS is
the classic O(n²) IoU matrix + sequential suppression via lax.scan over
score rank (no dynamic shapes: outputs are index/keep vectors padded to
the input size), so the whole family jits and differentiates where it
makes sense.  Boxes are [N, 4] (x1, y1, x2, y2) unless noted.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from ..core.autograd import apply
from ..core.tensor import Tensor, unwrap as _arr

__all__ = ["box_area", "box_iou", "iou_similarity", "box_clip",
           "box_coder", "nms", "multiclass_nms", "prior_box",
           "generate_anchors", "detection_map", "roi_align", "roi_pool"]




def box_area(boxes):
    def fn(b):
        return jnp.clip(b[..., 2] - b[..., 0], 0) * \
            jnp.clip(b[..., 3] - b[..., 1], 0)
    return apply(fn, boxes, name="box_area")


def box_iou(boxes1, boxes2):
    """Pairwise IoU [N, M] (bbox_util.h JaccardOverlap)."""
    def fn(a, b):
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * \
            jnp.clip(a[:, 3] - a[:, 1], 0)
        area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * \
            jnp.clip(b[:, 3] - b[:, 1], 0)
        union = area_a[:, None] + area_b[None, :] - inter
        return inter / jnp.maximum(union, 1e-10)
    return apply(fn, boxes1, boxes2, name="box_iou")


iou_similarity = box_iou  # reference iou_similarity_op name


def box_clip(boxes, im_shape):
    """Clip boxes into the image (box_clip_op.h). im_shape: (h, w)."""
    h, w = (float(im_shape[0]), float(im_shape[1]))

    def fn(b):
        return jnp.stack([
            jnp.clip(b[..., 0], 0, w), jnp.clip(b[..., 1], 0, h),
            jnp.clip(b[..., 2], 0, w), jnp.clip(b[..., 3], 0, h),
        ], axis=-1)
    return apply(fn, boxes, name="box_clip")


def box_coder(prior_boxes, target, code_type="encode_center_size",
              variance: Optional[Sequence[float]] = None):
    """Encode gt boxes against anchors / decode deltas back to boxes
    (box_coder_op.h EncodeCenterSize / DecodeCenterSize)."""
    var = jnp.asarray(variance if variance is not None
                      else (1.0, 1.0, 1.0, 1.0), jnp.float32)

    def enc(p, t):
        pw = p[..., 2] - p[..., 0]
        ph = p[..., 3] - p[..., 1]
        pcx = p[..., 0] + 0.5 * pw
        pcy = p[..., 1] + 0.5 * ph
        tw = t[..., 2] - t[..., 0]
        th = t[..., 3] - t[..., 1]
        tcx = t[..., 0] + 0.5 * tw
        tcy = t[..., 1] + 0.5 * th
        return jnp.stack([
            (tcx - pcx) / pw / var[0], (tcy - pcy) / ph / var[1],
            jnp.log(jnp.maximum(tw / pw, 1e-10)) / var[2],
            jnp.log(jnp.maximum(th / ph, 1e-10)) / var[3],
        ], axis=-1)

    def dec(p, d):
        pw = p[..., 2] - p[..., 0]
        ph = p[..., 3] - p[..., 1]
        pcx = p[..., 0] + 0.5 * pw
        pcy = p[..., 1] + 0.5 * ph
        cx = d[..., 0] * var[0] * pw + pcx
        cy = d[..., 1] * var[1] * ph + pcy
        w = jnp.exp(d[..., 2] * var[2]) * pw
        h = jnp.exp(d[..., 3] * var[3]) * ph
        return jnp.stack([cx - 0.5 * w, cy - 0.5 * h,
                          cx + 0.5 * w, cy + 0.5 * h], axis=-1)

    fn = enc if code_type.startswith("encode") else dec
    return apply(fn, prior_boxes, target, name="box_coder")


def nms(boxes, scores, iou_threshold=0.5, score_threshold=None,
        top_k: Optional[int] = None):
    """Greedy NMS (multiclass_nms_op.cc NMSFast). Returns kept indices
    by descending score — a Tensor of int32 (eager: trimmed to the kept
    count; the jit-safe core keeps a fixed-size keep mask)."""
    b = _arr(boxes).astype(jnp.float32)
    s = _arr(scores).astype(jnp.float32)
    keep = _nms_mask(b, s, float(iou_threshold),
                     -jnp.inf if score_threshold is None
                     else float(score_threshold))
    order = jnp.argsort(-s)
    kept = order[keep[order]]
    if top_k is not None:
        kept = kept[:top_k]
    return Tensor(kept.astype(jnp.int32))


def _nms_mask(b, s, iou_thr, score_thr):
    """Fixed-shape NMS core: scan over score rank, suppressing against
    the accumulated keep set (jit-friendly: no dynamic shapes)."""
    n = b.shape[0]
    if n == 0:
        return jnp.zeros((0,), bool)
    lt = jnp.maximum(b[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(b[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    iou = inter / jnp.maximum(area[:, None] + area[None, :] - inter,
                              1e-10)
    order = jnp.argsort(-s)

    def body(keep, i):
        idx = order[i]
        ok = (s[idx] > score_thr) & \
            ~jnp.any(keep & (iou[idx] > iou_thr))
        return keep.at[idx].set(ok), None

    keep0 = jnp.zeros((n,), bool)
    keep, _ = jax.lax.scan(body, keep0, jnp.arange(n))
    return keep


def multiclass_nms(bboxes, scores, score_threshold=0.05, nms_top_k=400,
                   keep_top_k=100, nms_threshold=0.3):
    """Per-class NMS + cross-class top-k (multiclass_nms_op.cc).
    bboxes: [N, 4]; scores: [C, N]. Returns [M, 6] rows of
    (class, score, x1, y1, x2, y2), best first. Host-trimmed output."""
    b = np.asarray(_arr(bboxes), np.float32)
    sc = np.asarray(_arr(scores), np.float32)
    rows = []
    for c in range(sc.shape[0]):
        s = sc[c]
        cand = np.nonzero(s > score_threshold)[0]
        if len(cand) == 0:
            continue
        cand = cand[np.argsort(-s[cand])][:nms_top_k]
        kept = np.asarray(nms(b[cand], s[cand],
                              iou_threshold=nms_threshold).data)
        for i in kept:
            gi = cand[int(i)]
            rows.append((float(c), float(s[gi]), *b[gi].tolist()))
    rows.sort(key=lambda r: -r[1])
    rows = rows[:keep_top_k]
    out = np.asarray(rows, np.float32).reshape(-1, 6)
    return Tensor(jnp.asarray(out))


def prior_box(feature_h, feature_w, image_h, image_w, min_sizes,
              max_sizes=(), aspect_ratios=(1.0,), flip=False,
              step=None, offset=0.5, clip=False):
    """SSD prior boxes over a feature grid (prior_box_op.h). Returns
    [H, W, A, 4] in normalized (x1, y1, x2, y2)."""
    ars = list(aspect_ratios)
    if flip:
        ars = ars + [1.0 / a for a in aspect_ratios if a != 1.0]
    step_x = step or image_w / feature_w
    step_y = step or image_h / feature_h
    whs = []
    for ms in min_sizes:
        for a in ars:
            whs.append((ms * np.sqrt(a), ms / np.sqrt(a)))
        for Ms in max_sizes:
            whs.append((np.sqrt(ms * Ms), np.sqrt(ms * Ms)))
    whs = np.asarray(whs, np.float32)              # [A, 2]
    cx = (np.arange(feature_w) + offset) * step_x  # [W]
    cy = (np.arange(feature_h) + offset) * step_y  # [H]
    cxg, cyg = np.meshgrid(cx, cy)                 # [H, W]
    centers = np.stack([cxg, cyg], -1)[:, :, None, :]      # [H,W,1,2]
    half = whs[None, None, :, :] / 2
    out = np.concatenate([centers - half, centers + half], -1)
    out = out / np.asarray([image_w, image_h, image_w, image_h],
                           np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    return Tensor(jnp.asarray(out.astype(np.float32)))


def _roi_grid(ba, ph, pw, sr, spatial_scale, off):
    """Shared RoI -> sample-point construction (pixel coords):
    returns (fx, fy) [R, ph*sr, pw*sr] sample centers."""
    x1 = ba[:, 0] * spatial_scale - off
    y1 = ba[:, 1] * spatial_scale - off
    rw = jnp.maximum(ba[:, 2] * spatial_scale - off - x1, 1e-3)
    rh = jnp.maximum(ba[:, 3] * spatial_scale - off - y1, 1e-3)
    ys = (jnp.arange(ph * sr) + 0.5) / sr          # bin units
    xs = (jnp.arange(pw * sr) + 0.5) / sr
    gy = y1[:, None] + rh[:, None] * ys[None, :] / ph   # [R, ph*sr]
    gx = x1[:, None] + rw[:, None] * xs[None, :] / pw   # [R, pw*sr]
    r = ba.shape[0]
    fy = jnp.broadcast_to(gy[:, :, None], (r, ph * sr, pw * sr))
    fx = jnp.broadcast_to(gx[:, None, :], (r, ph * sr, pw * sr))
    return fx, fy


def _roi_bilinear(xa, img_of, fx, fy):
    """Bilinear-sample feature map points per RoI WITHOUT materializing
    per-RoI feature copies: gathers only the sampled points.
    xa [N, C, H, W]; img_of [R]; fx/fy [R, hs, ws] pixel coords.
    Border rule matches roi_align_op.h bilinear_interpolate: coords in
    [-1, 0] (or [size-1, size]) clamp to the border pixel with full
    weight; only points beyond that contribute zero."""
    n, c, h, w = xa.shape
    b = img_of[:, None, None]
    valid = (fx >= -1.0) & (fx <= w) & (fy >= -1.0) & (fy <= h)
    fxc = jnp.clip(fx, 0.0, w - 1.0)
    fyc = jnp.clip(fy, 0.0, h - 1.0)
    x0 = jnp.floor(fxc).astype(jnp.int32)
    y0 = jnp.floor(fyc).astype(jnp.int32)
    x1 = jnp.minimum(x0 + 1, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    wx = (fxc - x0).astype(xa.dtype)[..., None]
    wy = (fyc - y0).astype(xa.dtype)[..., None]

    def take(ix, iy):
        return xa[b, :, iy, ix]                    # [R, hs, ws, C]

    out = (take(x0, y0) * (1 - wx) * (1 - wy) +
           take(x1, y0) * wx * (1 - wy) +
           take(x0, y1) * (1 - wx) * wy +
           take(x1, y1) * wx * wy)
    return jnp.where(valid[..., None], out, 0.0)


def _img_of(boxes_num, n, r):
    if boxes_num is None:
        return jnp.zeros((r,), jnp.int32)
    bn = jnp.asarray(_arr(boxes_num), jnp.int32)
    return jnp.repeat(jnp.arange(n, dtype=jnp.int32), bn,
                      total_repeat_length=r)


def _resolve_sr(sampling_ratio):
    # the reference's sampling_ratio<=0 means per-RoI ADAPTIVE sampling
    # (ceil(roi/pooled)); XLA needs static shapes, so a fixed 2x2 grid
    # per bin stands in — values differ slightly from the adaptive
    # kernel for large RoIs
    return 2 if sampling_ratio <= 0 else int(sampling_ratio)


def roi_align(x, boxes, boxes_num=None, output_size=7,
              spatial_scale=1.0, sampling_ratio=2, aligned=True):
    """RoIAlign (roi_align_op.h): bilinear-sample each RoI into a fixed
    [C, P, P] grid.  x: [N, C, H, W]; boxes: [R, 4] in image coords with
    boxes_num [N] mapping rows to batch images ([R] rois assumed all on
    image 0 when boxes_num is None).  Differentiable in x."""
    ps = (output_size if isinstance(output_size, (tuple, list))
          else (output_size, output_size))
    ph, pw = int(ps[0]), int(ps[1])
    sr = _resolve_sr(sampling_ratio)
    off = 0.5 if aligned else 0.0

    def fn(xa, ba):
        n, ch = xa.shape[0], xa.shape[1]
        r = ba.shape[0]
        img_of = _img_of(boxes_num, n, r)
        fx, fy = _roi_grid(ba.astype(jnp.float32), ph, pw, sr,
                           spatial_scale, off)
        sam = _roi_bilinear(xa, img_of, fx, fy)     # [R, hs, ws, C]
        sam = jnp.moveaxis(sam, -1, 1).reshape(r, ch, ph, sr, pw, sr)
        return sam.mean(axis=(3, 5))

    return apply(fn, x, boxes, name="roi_align")


def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0):
    """RoIPool (roi_pool_op.h): max over each bin.  Implemented as dense
    bilinear sampling followed by max (fixed shapes; exact argmax-bin
    parity is not preserved for degenerate rois).  Differentiable in x."""
    ps = (output_size if isinstance(output_size, (tuple, list))
          else (output_size, output_size))
    ph, pw = int(ps[0]), int(ps[1])
    sr = 2

    def fn(xa, ba):
        n, ch = xa.shape[0], xa.shape[1]
        r = ba.shape[0]
        img_of = _img_of(boxes_num, n, r)
        fx, fy = _roi_grid(ba.astype(jnp.float32), ph, pw, sr,
                           spatial_scale, 0.0)
        sam = _roi_bilinear(xa, img_of, fx, fy)
        sam = jnp.moveaxis(sam, -1, 1).reshape(r, ch, ph, sr, pw, sr)
        return sam.max(axis=(3, 5))

    return apply(fn, x, boxes, name="roi_pool")


def detection_map(detections, gt_boxes, gt_labels,
                  overlap_threshold=0.5, ap_version="integral"):
    """Mean average precision over a detection set
    (reference detection_map_op.cc / fluid/metrics.py DetectionMAP).

    detections: list per image of [M, 6] rows (class, score, x1..y2)
    (multiclass_nms output); gt_boxes/gt_labels: lists per image of
    [G, 4] and [G].  ap_version: 'integral' (VOC2010 AUC) or '11point'.
    Host-side metric math, like the reference's CPU-only op.
    """
    per_class = {}
    npos = {}
    for img, (det, gtb, gtl) in enumerate(
            zip(detections, gt_boxes, gt_labels)):
        det = np.asarray(_arr(det), np.float32).reshape(-1, 6)
        gtb = np.asarray(_arr(gtb), np.float32).reshape(-1, 4)
        gtl = np.asarray(_arr(gtl)).reshape(-1).astype(np.int64)
        for c in gtl:
            npos[int(c)] = npos.get(int(c), 0) + 1
        matched = np.zeros(len(gtb), bool)
        for row in det[np.argsort(-det[:, 1])]:
            c, score = int(row[0]), float(row[1])
            cand = np.nonzero(gtl == c)[0]
            best, best_iou = -1, overlap_threshold
            if len(cand):
                ious = np.asarray(box_iou(
                    row[None, 2:6], gtb[cand]).data)[0]
                j = int(np.argmax(ious))
                if ious[j] >= best_iou and not matched[cand[j]]:
                    best = cand[j]
            tp = best >= 0
            if tp:
                matched[best] = True
            per_class.setdefault(c, []).append((score, tp))
    aps = []
    for c, rows in per_class.items():
        rows.sort(key=lambda r: -r[0])
        tps = np.cumsum([r[1] for r in rows])
        fps = np.cumsum([not r[1] for r in rows])
        recall = tps / max(npos.get(c, 0), 1)
        precision = tps / np.maximum(tps + fps, 1)
        if ap_version == "11point":
            ap = float(np.mean([
                precision[recall >= t].max() if (recall >= t).any()
                else 0.0 for t in np.linspace(0, 1, 11)]))
        else:  # integral: area under interpolated PR curve
            prec = np.maximum.accumulate(precision[::-1])[::-1]
            rec = np.concatenate([[0.0], recall])
            ap = float(np.sum((rec[1:] - rec[:-1]) * prec))
        aps.append(ap)
    return float(np.mean(aps)) if aps else 0.0


def generate_anchors(feature_h, feature_w, stride, sizes=(32, 64, 128),
                     aspect_ratios=(0.5, 1.0, 2.0)):
    """RPN-style anchors (anchor_generator_op.h): [H, W, A, 4] in image
    coordinates."""
    whs = []
    for sz in sizes:
        for a in aspect_ratios:
            whs.append((sz * np.sqrt(a), sz / np.sqrt(a)))
    whs = np.asarray(whs, np.float32)
    cx = (np.arange(feature_w) + 0.5) * stride
    cy = (np.arange(feature_h) + 0.5) * stride
    cxg, cyg = np.meshgrid(cx, cy)
    centers = np.stack([cxg, cyg], -1)[:, :, None, :]
    half = whs[None, None, :, :] / 2
    out = np.concatenate([centers - half, centers + half], -1)
    return Tensor(jnp.asarray(out.astype(np.float32)))


# ---------------------------------------------------------------------------
# deformable convolution (reference operators/deformable_conv_op.cc /
# deformable_conv_v1_op.cc, modulated_deformable_im2col kernels)
# ---------------------------------------------------------------------------
def _bilinear_zero(img, ys, xs):
    """Sample img [C, H, W] at float (ys, xs) [...] with zero padding."""
    H, W = img.shape[-2:]
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def tap(yi, xi):
        inside = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]                      # [C, ...]
        return v * inside.astype(img.dtype)

    v00 = tap(y0, x0)
    v01 = tap(y0, x0 + 1)
    v10 = tap(y0 + 1, x0)
    v11 = tap(y0 + 1, x0 + 1)
    wy = wy.astype(img.dtype)
    wx = wx.astype(img.dtype)
    return (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
            + v10 * wy * (1 - wx) + v11 * wy * wx)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference deformable_conv_op.cc; v1 when
    mask is None). x [N,Cin,H,W], offset [N,2*dg*kh*kw,Ho,Wo] with the
    reference's (dy, dx) channel pairing, mask [N,dg*kh*kw,Ho,Wo],
    weight [Cout,Cin/groups,kh,kw]."""
    sh, sw = (stride, stride) if isinstance(stride, int) else tuple(stride)
    ph, pw = (padding, padding) if isinstance(padding, int) \
        else tuple(padding)
    dh, dw = (dilation, dilation) if isinstance(dilation, int) \
        else tuple(dilation)
    dg, g = deformable_groups, groups

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)

    def fn(xa, off, w, *rest):
        m = rest[0] if mask is not None else None
        b = rest[-1] if bias is not None else None
        N, Cin, H, W = xa.shape
        Cout, _, kh, kw = w.shape
        K = kh * kw
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

        off = off.reshape(N, dg, K, 2, Ho, Wo)
        # base grid: output position -> kernel tap coordinates
        iy = (jnp.arange(Ho) * sh - ph)[:, None]            # [Ho, 1]
        ix = (jnp.arange(Wo) * sw - pw)[None, :]            # [1, Wo]
        ty = jnp.repeat(jnp.arange(kh) * dh, kw)            # [K] tap bases
        tx = jnp.tile(jnp.arange(kw) * dw, kh)
        ys = iy[None, :, :] + ty[:, None, None]             # [K, Ho, Wo]
        xs = ix[None, :, :] + tx[:, None, None]
        ys = ys[None, None] + off[:, :, :, 0]               # [N,dg,K,Ho,Wo]
        xs = xs[None, None] + off[:, :, :, 1]

        xg = xa.reshape(N, dg, Cin // dg, H, W)

        def sample_one(img, ysv, xsv):
            return _bilinear_zero(img, ysv, xsv)            # [C, K,Ho,Wo]

        cols = jax.vmap(jax.vmap(sample_one))(xg, ys, xs)
        # cols [N, dg, Cin//dg, K, Ho, Wo]
        if m is not None:
            mm = m.reshape(N, dg, 1, K, Ho, Wo).astype(cols.dtype)
            cols = cols * mm
        cols = cols.reshape(N, Cin, K, Ho, Wo)
        cols = cols.reshape(N, g, Cin // g, K, Ho, Wo)
        wgt = w.reshape(g, Cout // g, Cin // g, K)
        out = jnp.einsum("ngckhw,gock->ngohw", cols, wgt)
        out = out.reshape(N, Cout, Ho, Wo)
        if b is not None:
            out = out + b.reshape(1, -1, 1, 1)
        return out

    return apply(fn, *args, name="deform_conv2d")


from ..nn.layer_base import Layer as _Layer  # noqa: E402


class DeformConv2D(_Layer):
    """Layer wrapper over deform_conv2d (reference
    python/paddle/vision/ops.py DeformConv2D)."""

    def __init__(self, in_channels, out_channels, kernel_size,
                 stride=1, padding=0, dilation=1, deformable_groups=1,
                 groups=1, weight_attr=None, bias_attr=None):
        super().__init__()
        import math
        from ..nn import initializer as I
        kh, kw = (kernel_size, kernel_size) \
            if isinstance(kernel_size, int) else tuple(kernel_size)
        self._args = (stride, padding, dilation, deformable_groups,
                      groups)
        bound = math.sqrt(1.0 / (in_channels // groups * kh * kw))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, kh, kw],
            attr=weight_attr,
            default_initializer=I.Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._args
        return deform_conv2d(x, offset, self.weight, self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


# ---------------------------------------------------------------------------
# YOLO ops (reference operators/detection/yolo_box_op.cc, yolov3_loss_op.cc)
# ---------------------------------------------------------------------------
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0, name=None):
    """Decode YOLOv3 head output to boxes+scores (yolo_box_op.cc).
    x [N, an*(5+C), H, W]; img_size [N, 2] (h, w). Returns
    (boxes [N, an*H*W, 4] xyxy in image coords, scores [N, an*H*W, C]);
    predictions under conf_thresh are zeroed like the reference."""
    an = len(anchors) // 2
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(an, 2))
    C = class_num
    sxy = float(scale_x_y)

    def fn(xa, imsz):
        N, _, H, W = xa.shape
        in_h, in_w = H * downsample_ratio, W * downsample_ratio
        p = xa.reshape(N, an, 5 + C, H, W)
        gx = jnp.arange(W, dtype=jnp.float32)[None, :]
        gy = jnp.arange(H, dtype=jnp.float32)[:, None]
        sig = jax.nn.sigmoid
        bx = (sig(p[:, :, 0]) * sxy - 0.5 * (sxy - 1.0) + gx) / W
        by = (sig(p[:, :, 1]) * sxy - 0.5 * (sxy - 1.0) + gy) / H
        bw = jnp.exp(p[:, :, 2]) * anc[None, :, 0, None, None] / in_w
        bh = jnp.exp(p[:, :, 3]) * anc[None, :, 1, None, None] / in_h
        conf = sig(p[:, :, 4])
        keep = (conf >= conf_thresh).astype(xa.dtype)
        scores = sig(p[:, :, 5:]) * (conf * keep)[:, :, None]
        imh = imsz[:, 0].astype(jnp.float32)[:, None, None, None]
        imw = imsz[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * keep[..., None]
        boxes = boxes.reshape(N, -1, 4)
        scores = jnp.moveaxis(scores, 2, -1).reshape(N, -1, C)
        return boxes, scores

    out = apply(fn, x, img_size, name="yolo_box")
    return out[0], out[1]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 training loss for one detection head (yolov3_loss_op.cc).

    x [N, am*(5+C), H, W]; gt_box [N, B, 4] (cx, cy, w, h normalized to
    the image, zero-padded); gt_label [N, B] int. Reference semantics:
    each gt is matched to its best anchor over ALL anchors by wh-IoU; if
    that anchor belongs to this head's anchor_mask the gt is assigned to
    its cell. x/y use sigmoid BCE, w/h use L1, objectness BCE with the
    ignore mask (pred-gt IoU > ignore_thresh), class BCE — coordinate
    terms weighted by (2 - gw*gh). Returns per-sample loss [N]."""
    all_anc = np.asarray(anchors, np.float32).reshape(-1, 2)
    mask_idx = list(anchor_mask)
    am = len(mask_idx)
    C = class_num
    smooth = (1.0 / max(C, 1)) if (use_label_smooth and C > 1) else 0.0
    # label smoothing delta matches the reference: 1/class_num

    def fn(xa, gtb, gtl, gts):
        N, _, H, W = xa.shape
        B = gtb.shape[1]
        in_h = jnp.float32(H * downsample_ratio)
        in_w = jnp.float32(W * downsample_ratio)
        p = xa.reshape(N, am, 5 + C, H, W)
        sig = jax.nn.sigmoid
        anc = jnp.asarray(all_anc)                       # [A, 2] pixels
        head = anc[jnp.asarray(mask_idx)]                # [am, 2]

        # ---- gt -> best anchor over ALL anchors (wh IoU, centered)
        gw = gtb[:, :, 2] * in_w                         # [N, B] pixels
        gh = gtb[:, :, 3] * in_h
        inter = jnp.minimum(gw[..., None], anc[None, None, :, 0]) * \
            jnp.minimum(gh[..., None], anc[None, None, :, 1])
        union = gw[..., None] * gh[..., None] + \
            anc[None, None, :, 0] * anc[None, None, :, 1] - inter
        wh_iou = inter / jnp.maximum(union, 1e-9)        # [N, B, A]
        best = jnp.argmax(wh_iou, axis=2)                # [N, B]
        valid = (gtb[:, :, 2] > 0) & (gtb[:, :, 3] > 0)

        # cell assignment
        gi = jnp.clip((gtb[:, :, 0] * W).astype(jnp.int32), 0, W - 1)
        gj = jnp.clip((gtb[:, :, 1] * H).astype(jnp.int32), 0, H - 1)

        # one-hot scatter of targets onto [N, am, H, W].  Targets
        # accumulate with BINARY weights (normalized by the cell's gt
        # count below); gt_score accumulates separately so mixup-style
        # fractional scores weight the positive loss terms like the
        # reference (yolov3_loss_op.h score-scaled obj/coord/class)
        def build_targets(n_gtb, n_gtl, n_gts, n_best, n_valid, n_gi,
                          n_gj):
            tgt = jnp.zeros((am, 6 + C, H, W), jnp.float32)
            cnt = jnp.zeros((am, H, W), jnp.float32)
            scr = jnp.zeros((am, H, W), jnp.float32)
            for k, a_id in enumerate(mask_idx):
                sel = (n_valid & (n_best == a_id)).astype(jnp.float32)
                tx = n_gtb[:, 0] * W - jnp.floor(n_gtb[:, 0] * W)
                ty = n_gtb[:, 1] * H - jnp.floor(n_gtb[:, 1] * H)
                tw = jnp.log(jnp.maximum(
                    n_gtb[:, 2] * in_w / head[k, 0], 1e-9))
                th = jnp.log(jnp.maximum(
                    n_gtb[:, 3] * in_h / head[k, 1], 1e-9))
                box_w = 2.0 - n_gtb[:, 2] * n_gtb[:, 3]
                cls1 = jax.nn.one_hot(n_gtl, C) * (1.0 - smooth) + \
                    smooth / max(C, 1)
                rows = jnp.stack([tx, ty, tw, th,
                                  jnp.ones_like(tx), box_w], axis=1)
                rows = jnp.concatenate([rows, cls1], axis=1)  # [B, 6+C]
                upd = jnp.zeros((6 + C, H, W)).at[:, n_gj, n_gi].add(
                    (rows * sel[:, None]).T)
                tgt = tgt.at[k].add(upd)
                cnt = cnt.at[k].add(
                    jnp.zeros((H, W)).at[n_gj, n_gi].add(sel))
                scr = scr.at[k].add(
                    jnp.zeros((H, W)).at[n_gj, n_gi].add(sel * n_gts))
            return tgt, cnt, scr

        gts_ = jnp.ones((N, B), jnp.float32) if gts is None else gts
        tgt, found, score_sum = jax.vmap(build_targets)(
            gtb, gtl, gts_, best, valid, gi, gj)
        # found > 0 marks cells that own a gt (overlapping gts are
        # averaged by normalizing the accumulated targets)
        obj_mask = (found > 0).astype(jnp.float32)       # [N, am, H, W]
        norm2d = jnp.maximum(found, 1e-9)
        tgt = tgt / norm2d[:, :, None]
        score_map = score_sum / norm2d                   # avg gt_score

        # ---- ignore mask: predicted boxes with IoU>thresh vs any gt
        gx_ = jnp.arange(W, dtype=jnp.float32)[None, :]
        gy_ = jnp.arange(H, dtype=jnp.float32)[:, None]
        sxy = float(scale_x_y)
        bx = (sig(p[:, :, 0]) * sxy - 0.5 * (sxy - 1.0) + gx_) / W
        by = (sig(p[:, :, 1]) * sxy - 0.5 * (sxy - 1.0) + gy_) / H
        bw = jnp.exp(jnp.clip(p[:, :, 2], -10, 10)) * \
            head[None, :, 0, None, None] / in_w
        bh = jnp.exp(jnp.clip(p[:, :, 3], -10, 10)) * \
            head[None, :, 1, None, None] / in_h
        px1, px2 = bx - bw / 2, bx + bw / 2
        py1, py2 = by - bh / 2, by + bh / 2
        qx1 = gtb[:, :, 0] - gtb[:, :, 2] / 2
        qx2 = gtb[:, :, 0] + gtb[:, :, 2] / 2
        qy1 = gtb[:, :, 1] - gtb[:, :, 3] / 2
        qy2 = gtb[:, :, 1] + gtb[:, :, 3] / 2
        ix = jnp.maximum(
            jnp.minimum(px2[:, :, :, :, None],
                        qx2[:, None, None, None, :]) -
            jnp.maximum(px1[:, :, :, :, None],
                        qx1[:, None, None, None, :]), 0)
        iy = jnp.maximum(
            jnp.minimum(py2[:, :, :, :, None],
                        qy2[:, None, None, None, :]) -
            jnp.maximum(py1[:, :, :, :, None],
                        qy1[:, None, None, None, :]), 0)
        inter_p = ix * iy
        area_p = (px2 - px1) * (py2 - py1)
        area_g = ((qx2 - qx1) * (qy2 - qy1))[:, None, None, None, :]
        iou = inter_p / jnp.maximum(area_p[..., None] + area_g - inter_p,
                                    1e-9)
        iou = jnp.where(valid[:, None, None, None, :], iou, 0.0)
        ignore = (jnp.max(iou, axis=4) > ignore_thresh).astype(
            jnp.float32)
        noobj_mask = (1.0 - obj_mask) * (1.0 - ignore)

        def bce(logit, label):
            return jax.nn.softplus(logit) - label * logit

        # positive terms are gt_score-weighted (mixup), like the
        # reference's score-scaled loss
        pos_w = obj_mask * score_map
        box_w = tgt[:, :, 5]
        loss_xy = box_w * pos_w * (
            bce(p[:, :, 0], tgt[:, :, 0]) + bce(p[:, :, 1], tgt[:, :, 1]))
        loss_wh = box_w * pos_w * (
            jnp.abs(p[:, :, 2] - tgt[:, :, 2]) +
            jnp.abs(p[:, :, 3] - tgt[:, :, 3]))
        loss_obj = pos_w * bce(p[:, :, 4], jnp.ones_like(obj_mask)) + \
            noobj_mask * bce(p[:, :, 4], jnp.zeros_like(obj_mask))
        cls_t = jnp.moveaxis(tgt[:, :, 6:], 2, -1)       # [N,am,H,W,C]
        cls_p = jnp.moveaxis(p[:, :, 5:], 2, -1)
        loss_cls = pos_w[..., None] * bce(cls_p, cls_t)
        total = (loss_xy.sum(axis=(1, 2, 3)) +
                 loss_wh.sum(axis=(1, 2, 3)) +
                 loss_obj.sum(axis=(1, 2, 3)) +
                 loss_cls.sum(axis=(1, 2, 3, 4)))
        return total

    if gt_score is not None:
        return apply(fn, x, gt_box, gt_label, gt_score,
                     name="yolo_loss")
    return apply(lambda a, b, c: fn(a, b, c, None), x, gt_box, gt_label,
                 name="yolo_loss")
