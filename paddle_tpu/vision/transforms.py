"""Image transforms (reference python/paddle/vision/transforms/ — numpy
backend; these run on host in DataLoader workers, feeding the device
pipeline)."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomRotation",
           "Grayscale", "to_tensor", "normalize", "resize", "hflip", "vflip"]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def to_tensor(pic, data_format="CHW"):
    img = _as_hwc(pic).astype(np.float32)
    if img.dtype == np.uint8 or img.max() > 1.5:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return img


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    ys = (np.arange(nh) + 0.5) * h / nh - 0.5
    xs = (np.arange(nw) + 0.5) * w / nw - 0.5
    if interpolation == "nearest":
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        return img[yi][:, xi]
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0, 1)[:, None, None]
    wx = np.clip(xs - x0, 0, 1)[None, :, None]
    a = img[y0][:, x0]
    b = img[y0][:, x1]
    c = img[y1][:, x0]
    d = img[y1][:, x1]
    out = (a * (1 - wy) * (1 - wx) + b * (1 - wy) * wx +
           c * wy * (1 - wx) + d * wy * wx)
    return out.astype(img.dtype if img.dtype != np.uint8 else np.float32)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        n = img.shape[0 if self.data_format == "CHW" else -1]
        mean = (self.mean * n)[:n] if len(self.mean) < n else self.mean[:n]
        std = (self.std * n)[:n] if len(self.std) < n else self.std[:n]
        return normalize(img, mean, std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = (size, size) if isinstance(size, int) else size

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        th, tw = self.size
        i = max(0, (h - th) // 2)
        j = max(0, (w - tw) // 2)
        return img[i:i + th, j:j + tw]


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) \
                else [self.padding] * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        i = pyrandom.randint(0, max(0, h - th))
        j = pyrandom.randint(0, max(0, w - tw))
        return img[i:i + th, j:j + tw]


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return hflip(img)
        return _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if pyrandom.random() < self.prob:
            return vflip(img)
        return _as_hwc(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        alpha = 1 + np.random.uniform(-self.value, self.value)
        return np.clip(_as_hwc(img) * alpha, 0, 255).astype(np.float32)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding = padding if isinstance(padding, (list, tuple)) else \
            [padding] * 4
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        p = self.padding
        if len(p) == 2:
            p = [p[0], p[1], p[0], p[1]]
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)),
                      constant_values=self.fill)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees

    def _apply_image(self, img):
        img = _as_hwc(img)
        angle = np.random.uniform(*self.degrees)
        # nearest-neighbor rotation about center
        h, w = img.shape[:2]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        rad = np.deg2rad(angle)
        yy, xx = np.mgrid[0:h, 0:w]
        ys = cy + (yy - cy) * np.cos(rad) - (xx - cx) * np.sin(rad)
        xs = cx + (yy - cy) * np.sin(rad) + (xx - cx) * np.cos(rad)
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        valid = (ys >= 0) & (ys < h) & (xs >= 0) & (xs < w)
        out = img[yi, xi]
        out[~valid] = 0
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.n = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        if img.shape[2] == 1:
            g = img
        else:
            g = (0.299 * img[..., 0:1] + 0.587 * img[..., 1:2] +
                 0.114 * img[..., 2:3])
        return np.repeat(g, self.n, axis=2) if self.n > 1 else g
