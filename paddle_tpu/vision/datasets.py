"""Vision datasets (reference python/paddle/vision/datasets/ — MNIST,
FashionMNIST, Cifar10/100, Flowers, ImageFolder/DatasetFolder).

Zero-egress environments (this one) can't download; each dataset reads
the standard local file formats when present and otherwise raises with a
clear message. `SyntheticMNIST`-style deterministic data for tests/bench
is available via `mode='synthetic'` or FakeData."""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
from typing import Callable, Optional

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "DatasetFolder",
           "ImageFolder", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic image classification data (not in the
    reference; used where its tests download MNIST)."""

    def __init__(self, size=1000, image_shape=(1, 28, 28), num_classes=10,
                 transform=None, seed=0, class_seed=1234):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        rng = np.random.RandomState(seed)
        self._labels = rng.randint(0, num_classes, size).astype(np.int64)
        # class prototypes come from class_seed so train/test splits with
        # different `seed` draw from the SAME distribution
        self._base = np.random.RandomState(class_seed).randn(
            num_classes, *self.image_shape).astype(np.float32)
        self._seed = seed

    def __getitem__(self, idx):
        lab = self._labels[idx]
        rng = np.random.RandomState(self._seed + idx)
        img = self._base[lab] + 0.3 * rng.randn(*self.image_shape) \
            .astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, lab

    def __len__(self):
        return self.size


class MNIST(Dataset):
    """reference vision/datasets/mnist.py — idx-ubyte file format."""

    NAME = "mnist"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        root = os.environ.get("PADDLE_TPU_DATA_HOME",
                              os.path.expanduser("~/.cache/paddle/dataset"))
        base = os.path.join(root, self.NAME)
        tag = "train" if self.mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{tag}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{tag}-labels-idx1-ubyte.gz")
        if self.mode == "synthetic" or not (
                os.path.exists(image_path) and os.path.exists(label_path)):
            if self.mode != "synthetic" and download:
                raise RuntimeError(
                    f"MNIST files not found at {image_path} and this "
                    "environment has no network egress. Place the idx-ubyte "
                    ".gz files there, or use "
                    "paddle_tpu.vision.datasets.FakeData for synthetic "
                    "data.")
            fake = FakeData(size=60000 if self.mode == "train" else 10000,
                            image_shape=(28, 28, 1), transform=None)
            self.images = np.stack(
                [fake[i][0] for i in range(256)])  # small synthetic slice
            self.labels = fake._labels[:256]
        else:
            self.images = self._read_images(image_path)
            self.labels = self._read_labels(label_path)

    @staticmethod
    def _read_images(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols, 1).astype(np.float32)

    @staticmethod
    def _read_labels(path):
        op = gzip.open if path.endswith(".gz") else open
        with op(path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    NAME = "fashion-mnist"


class Cifar10(Dataset):
    """reference vision/datasets/cifar.py — python-pickle batches."""

    N_CLASSES = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode.lower()
        self.transform = transform
        root = os.environ.get("PADDLE_TPU_DATA_HOME",
                              os.path.expanduser("~/.cache/paddle/dataset"))
        name = "cifar-10-python.tar.gz" if self.N_CLASSES == 10 else \
            "cifar-100-python.tar.gz"
        data_file = data_file or os.path.join(root, "cifar", name)
        if not os.path.exists(data_file):
            raise RuntimeError(
                f"Cifar archive not found at {data_file}; no network "
                "egress. Use FakeData for synthetic data.")
        self.data, self.labels = self._load(data_file)

    def _load(self, path):
        datas, labels = [], []
        want = ("data_batch" if self.mode == "train" else "test_batch") \
            if self.N_CLASSES == 10 else \
            ("train" if self.mode == "train" else "test")
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if want in member.name:
                    d = pickle.load(tf.extractfile(member),
                                    encoding="bytes")
                    datas.append(d[b"data"])
                    key = b"labels" if b"labels" in d else b"fine_labels"
                    labels.extend(d[key])
        data = np.concatenate(datas).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1).astype(np.float32)
        return data, np.asarray(labels, np.int64)

    def __getitem__(self, idx):
        img = self.data[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    N_CLASSES = 100


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """class-per-subdir image folder (reference
    vision/datasets/folder.py). Loader defaults to numpy (.npy) since
    PIL may be absent."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if is_valid_file is not None:
                    ok = is_valid_file(fname)
                else:
                    ok = fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append((os.path.join(d, fname),
                                         self.class_to_idx[c]))

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        try:
            from PIL import Image
            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError as e:
            raise RuntimeError(
                f"cannot load {path}: PIL unavailable; use .npy files or "
                "pass a custom loader") from e

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    """flat image folder without labels (reference folder.py:ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = [os.path.join(root, f)
                        for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(extensions))]

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)
