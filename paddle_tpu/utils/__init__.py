"""paddle.utils parity (subset)."""
from . import unique_name  # noqa: F401
