"""paddle.utils parity (subset)."""
from . import unique_name  # noqa: F401
from . import compile_cache  # noqa: F401
from . import tuning  # noqa: F401
