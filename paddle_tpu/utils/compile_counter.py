"""Process-wide XLA compile/trace counters.

PR 3 added the host-sync counter (distributed.async_dispatch) so tests
could PROVE "no per-step read-back" instead of hand-waving it; this is
the same discipline for compilation.  The serving engine's contract is
"the decode loop is recompile-free": after warmup, generating N tokens
must trigger ZERO new XLA compilations (a shape that changes per token —
the old concat-grown KV cache — would show up here as one compile per
generated token).

Counting uses ``jax.monitoring``, which jax fires around its own
compilation pipeline:

- ``/jax/core/compile/backend_compile_duration`` — one event per REAL
  XLA backend compile (persistent-cache deserializations do not fire it);
- ``/jax/core/compile/jaxpr_trace_duration`` — one event per jaxpr
  trace.  A persistent-cache hit still traces+lowers, so a decode loop
  whose shapes wobble is caught by the trace counter even when a warm
  on-disk cache hides the backend compile.

Listeners are registered lazily and exactly once; jax keeps them for the
process lifetime (there is no unregister-by-context), so the counters
are monotone — bracket a region with ``snapshot()`` and subtract.
"""
from __future__ import annotations

import contextlib
import threading

__all__ = ["install", "xla_compile_count", "xla_trace_count",
           "compile_counts", "CompileCountSnapshot", "snapshot",
           "assert_no_recompiles"]

_lock = threading.Lock()
_STATE = {"installed": False, "compiles": 0, "traces": 0}
_METRICS = {}                    # lazily-bound registry children

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"


def _listener(key: str, duration: float, **kwargs) -> None:
    # registry mirror updated under the same lock: compiles can fire
    # from any thread, and += on a shared child is not atomic
    if key == _COMPILE_EVENT:
        with _lock:
            _STATE["compiles"] += 1
            _METRICS["compiles"].inc()
        # flight-recorder event log: "what compiled, when" is exactly
        # the post-mortem question a recompile-churn hang raises.
        # Compiles are rare after warmup, so this is a cold path.
        from ..observability import flightrec as _flightrec
        _flightrec.note_event("xla_compile",
                              n=_STATE["compiles"],
                              duration_s=round(float(duration), 4))
    elif key == _TRACE_EVENT:
        with _lock:
            _STATE["traces"] += 1
            _METRICS["traces"].inc()


def install() -> bool:
    """Register the monitoring listener (idempotent). Returns True when
    the counters are live, False when jax's monitoring API is missing
    (counters then stay at 0 — callers must treat 0-delta as 'no
    evidence of a recompile', which is still the correct assertion
    direction for the recompile-free contract)."""
    with _lock:
        if _STATE["installed"]:
            return True
        # mirror into the unified metrics registry (observability/):
        # children bound before the listener can fire
        from ..observability import metrics as _obs_metrics
        _METRICS["compiles"] = _obs_metrics.counter(
            "xla_compiles_total", "XLA backend compiles")
        _METRICS["traces"] = _obs_metrics.counter(
            "jaxpr_traces_total", "jaxpr traces")
        try:
            from jax._src import monitoring
            monitoring.register_event_duration_secs_listener(_listener)
        except Exception:  # pragma: no cover - jax internals moved
            return False
        _STATE["installed"] = True
        return True


def xla_compile_count() -> int:
    """Total XLA backend compiles observed in this process."""
    install()
    return _STATE["compiles"]


def xla_trace_count() -> int:
    """Total jaxpr traces observed in this process."""
    install()
    return _STATE["traces"]


def compile_counts() -> dict:
    install()
    with _lock:
        return {"xla_compiles": _STATE["compiles"],
                "jaxpr_traces": _STATE["traces"]}


class CompileCountSnapshot:
    """Bracketing helper: ``snap = snapshot(); ...; snap.new_compiles``."""

    def __init__(self):
        install()
        self._c0 = _STATE["compiles"]
        self._t0 = _STATE["traces"]

    @property
    def new_compiles(self) -> int:
        return _STATE["compiles"] - self._c0

    @property
    def new_traces(self) -> int:
        return _STATE["traces"] - self._t0


def snapshot() -> CompileCountSnapshot:
    return CompileCountSnapshot()


@contextlib.contextmanager
def assert_no_recompiles(what: str = "region", traces: bool = True):
    """Bracket a region that MUST be recompile-free (a warmed decode
    loop, a Poisson load-test window): raises AssertionError on exit if
    any XLA backend compile — or, with ``traces=True``, any jaxpr trace
    (which catches shape wobbles a warm on-disk cache would hide) —
    happened inside.  The assertion form of the snapshot()/subtract
    idiom, so tests and the serving smokes share one spelling."""
    snap = snapshot()
    yield snap
    if snap.new_compiles:
        raise AssertionError(
            f"{snap.new_compiles} XLA compile(s) inside {what} "
            f"(expected 0 — a shape or dtype wobbled)")
    if traces and snap.new_traces:
        raise AssertionError(
            f"{snap.new_traces} jaxpr trace(s) inside {what} "
            f"(expected 0 — something re-traced even if the backend "
            f"compile was cached)")
