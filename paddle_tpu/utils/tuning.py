"""Unified per-(device_kind, shape, dtype) tuning table.

PR 1 gave flash attention a persistent block-size autotune table
(`ops/flash_attention.py`: process cache + atomic-rename JSON, corrupt-
tolerant load).  Every tunable knob since has wanted the same thing —
quantized-matmul tile sizes, the MoE all-to-all chunk count, the
engine's prefill bucket list — and re-growing that machinery per op
would mean four slightly different cache files.  This module is the
generalization: ONE store, namespaced by op, with the flash pattern
kept exactly:

- **process cache first** — a sweep result recorded in this process is
  authoritative for the process lifetime;
- **on-disk JSON second** — ``PADDLE_TPU_TUNING_CACHE`` names the file
  ("0"/"off" disables persistence; default
  ``~/.cache/paddle_tpu/tuning.json``).  Writes go through
  ``framework.fs.open_for_write`` (fsync before atomic rename), so a
  crash can never commit a truncated table;
- **corrupt-tolerant load** — an unreadable/garbage table is treated as
  empty (the next sweep re-measures and rewrites it), never raised;
- **opt-in sweeps** — ``PADDLE_TPU_TUNING=sweep`` arms the on-device
  sweeps of ops that have one (quantized-matmul tiles today; flash
  keeps its own ``PADDLE_TPU_FLASH_AUTOTUNE=sweep`` knob for
  compatibility, recording its winners here too).

Key format on disk: ``"<op>|<part>|<part>|..."`` with parts stringified
(bools as 0/1).  Consumers:

- ``ops.flash_attention.get_block_sizes`` — op ``flash_blocks``, key
  ``(device_kind, seq, head_dim, causal)``;
- ``ops.quantized_matmul`` — op ``qmm_tiles``, key
  ``(device_kind, m_bucket, n, k, dtype)``;
- ``distributed.overlap.moe_a2a_chunks`` — op ``moe_a2a_chunks``, key
  ``(device_kind, tokens)``;
- ``inference.engine.default_prefill_buckets`` — op
  ``prefill_buckets``, key ``(device_kind, max_seq_len)``.
"""
from __future__ import annotations

import json
import math
import os
import threading
from typing import Any, Dict, Optional, Tuple

__all__ = ["lookup", "lookup_nearest", "record", "entries", "tuning_path",
           "device_kind", "normalize_kind", "sweep_enabled", "key_str",
           "reset_for_tests", "provenance", "all_entries", "META_OP"]

# provenance rides the same flat "<op>|<part>|..." disk encoding under a
# reserved op namespace: "__meta__|<orig_op>|<part>|..." -> {source, run,
# improvement}.  Old tables simply have no __meta__ keys; old readers
# see __meta__ as just another op they never look up.
META_OP = "__meta__"

_lock = threading.RLock()
# op -> {key_tuple_of_strs: value}; merged from disk once, sweeps win
_STATE: Dict[str, Any] = {"loaded": False, "cache": {}}


# ---------------------------------------------------------------------------
# device identity (shared with flash_attention, which predates this module)
# ---------------------------------------------------------------------------
def normalize_kind(kind: str) -> str:
    """Canonical short device kind ('TPU v5 lite' -> 'v5e', ...)."""
    k = (kind or "").lower()
    for alias, canon in (("v5 lite", "v5e"), ("v5litepod", "v5e"),
                         ("v5e", "v5e"), ("v5p", "v5p"),
                         ("v6 lite", "v6e"), ("v6e", "v6e"),
                         ("v4", "v4"), ("v3", "v3"), ("v2", "v2")):
        if alias in k:
            return canon
    return k


def device_kind() -> str:
    """Normalized kind of the local default device ('' when unknown)."""
    try:
        import jax
        return normalize_kind(getattr(jax.devices()[0], "device_kind", ""))
    except Exception:  # pragma: no cover
        return ""


def sweep_enabled() -> bool:
    """The generic opt-in sweep knob (flash keeps its legacy env)."""
    return os.environ.get("PADDLE_TPU_TUNING", "").strip() == "sweep"


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------
def tuning_path() -> Optional[str]:
    p = os.environ.get("PADDLE_TPU_TUNING_CACHE", "").strip()
    if p.lower() in ("0", "off", "false", "none"):
        return None
    if p:
        return os.path.expanduser(p)
    base = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(base, "paddle_tpu", "tuning.json")


def key_str(op: str, parts) -> str:
    enc = [str(int(p)) if isinstance(p, bool) else str(p) for p in parts]
    return "|".join([op] + enc)


def _key_tuple(parts) -> Tuple[str, ...]:
    return tuple(str(int(p)) if isinstance(p, bool) else str(p)
                 for p in parts)


def _load_once() -> None:
    """Merge the on-disk table into the process cache (once); entries
    this process already recorded win over stale disk entries."""
    if _STATE["loaded"]:
        return
    _STATE["loaded"] = True
    path = tuning_path()
    if not path:
        return
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            return
        for k, v in data.items():
            parts = str(k).split("|")
            if len(parts) < 2:
                continue
            op, key = parts[0], tuple(parts[1:])
            _STATE["cache"].setdefault(op, {}).setdefault(key, v)
    except (OSError, ValueError, TypeError):
        pass  # corrupt/unreadable table: sweep again, then rewrite it


def lookup(op: str, parts) -> Any:
    """The tuned value for (op, key) or None. Process cache first, then
    the on-disk table (loaded once per process)."""
    with _lock:
        _load_once()
        return _STATE["cache"].get(op, {}).get(_key_tuple(parts))


def lookup_nearest(op: str, parts, match_idx, near_idx,
                   max_dist: Optional[float] = None) -> Any:
    """The tuned value for (op, key), falling back to the NEAREST tabled
    shape when the exact key is missing — the flash autotuner's
    nearest-seq behaviour generalized (a sweep at seq 2048 should not
    leave seq 1920 untuned).

    Candidates must string-equal the query at every ``match_idx``
    position (device kind, dtype, causal flag, ...); distance is the
    summed ``|log(query/candidate)|`` ratio over the ``near_idx``
    positions (all numeric — shape dims), so "half the size" and "twice
    the size" are equally near.  Non-numeric candidates at a near
    position are skipped.  ``max_dist`` caps the accepted distance —
    callers whose tuned value changes behaviour materially (a remat
    policy, not a tile clamp) should bound how far an entry may travel.
    Returns the best value or None."""
    exact = lookup(op, parts)
    if exact is not None:
        return exact
    q = _key_tuple(parts)
    best, best_d = None, None
    with _lock:
        _load_once()
        table = dict(_STATE["cache"].get(op, {}))
    for key, val in table.items():
        if len(key) != len(q):
            continue
        if any(key[i] != q[i] for i in match_idx):
            continue
        try:
            d = 0.0
            for i in near_idx:
                a, b = float(q[i]), float(key[i])
                if a <= 0 or b <= 0:
                    d += 0.0 if a == b else float("inf")
                else:
                    d += abs(math.log(a / b))
        except ValueError:
            continue
        if max_dist is not None and d > max_dist:
            continue
        if best_d is None or d < best_d:
            best, best_d = val, d
    return best


def entries(op: str) -> Dict[Tuple[str, ...], Any]:
    """All known entries for one op (copy)."""
    with _lock:
        _load_once()
        return dict(_STATE["cache"].get(op, {}))


def record(op: str, parts, value, *, source: Optional[str] = None,
           run: Optional[str] = None,
           improvement: Optional[float] = None) -> None:
    """Record a tuned value: process cache immediately, on-disk table
    best-effort via atomic read-modify-write (fsync before rename).

    ``source``/``run``/``improvement`` stamp provenance (ISSUE 16):
    who committed the entry ('sweep' | 'autotune' | 'manual'), under
    which BENCH_RUN / autotune run id, and the measured improvement
    fraction over the incumbent it beat.  Provenance lands in the same
    atomic write as the value — a crash can never commit one without
    the other."""
    meta = None
    if source is not None or run is not None or improvement is not None:
        meta = {"source": source or "manual"}
        if run:
            meta["run"] = str(run)
        if improvement is not None:
            meta["improvement"] = round(float(improvement), 6)
    with _lock:
        _load_once()
        _STATE["cache"].setdefault(op, {})[_key_tuple(parts)] = value
        if meta is not None:
            _STATE["cache"].setdefault(META_OP, {})[
                (op,) + _key_tuple(parts)] = meta
        path = tuning_path()
        if not path:
            return
        try:
            data = {}
            try:
                with open(path) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict):
                    data = loaded
            except (OSError, ValueError):
                pass  # corrupt table: overwrite with what we know
            data[key_str(op, parts)] = value
            if meta is not None:
                data[key_str(META_OP, (op,) + _key_tuple(parts))] = meta
            from ..framework.fs import open_for_write
            with open_for_write(path, "w") as f:
                json.dump(data, f, indent=0, sort_keys=True)
        except OSError:
            pass


def provenance(op: str, parts) -> Optional[Dict[str, Any]]:
    """The provenance stamp recorded with (op, key), or None (pre-16
    entries and plain record() calls carry none)."""
    with _lock:
        _load_once()
        m = _STATE["cache"].get(META_OP, {}).get((op,) + _key_tuple(parts))
        return dict(m) if isinstance(m, dict) else None


def all_entries() -> Dict[str, Dict[Tuple[str, ...], Any]]:
    """Every op's entries (copy), provenance namespace excluded — the
    report CLI's feed."""
    with _lock:
        _load_once()
        return {op: dict(t) for op, t in _STATE["cache"].items()
                if op != META_OP}


def reset_for_tests() -> None:
    """Drop the process cache so the next lookup re-reads the file
    (tests re-point PADDLE_TPU_TUNING_CACHE at tmp paths)."""
    with _lock:
        _STATE["loaded"] = False
        _STATE["cache"] = {}
