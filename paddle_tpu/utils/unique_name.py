"""Unique-name scopes.

Reference: python/paddle/utils/unique_name (generate/guard/switch over
per-prefix counters). Layer/parameter default names (linear_0.w_0 ...)
come from per-prefix counters in nn.layer_base; `guard()` swaps in a
fresh counter scope so models built inside it get deterministic names —
required when a checkpoint written by one process is restored by
another that has already built other layers (state-dict keys are
name-based, exactly like the reference's `param@moment` vars).
"""
from __future__ import annotations

import contextlib
from typing import Dict, Optional

__all__ = ["generate", "guard", "switch"]

_generate_counters: Dict[str, int] = {}
_prefix: str = ""


def generate(key: str) -> str:
    """reference unique_name.generate: key -> key_0, key_1, ... with the
    active guard's namespace prefix applied."""
    idx = _generate_counters.get(key, 0)
    _generate_counters[key] = idx + 1
    return f"{_prefix}{key}_{idx}"


def switch(new_counters: Optional[dict] = None):
    """Swap both the free-generate counters and the Layer naming
    counters; returns the previous (generate, layer) counter dicts."""
    from ..nn import layer_base
    global _generate_counters
    prev = (_generate_counters, dict(layer_base._layer_name_counters))
    _generate_counters = new_counters or {}
    layer_base._layer_name_counters.clear()
    return prev


@contextlib.contextmanager
def guard(new_generator: Optional[str] = None):
    """reference unique_name.guard: fresh name scope inside the
    context (optionally namespaced by a string prefix, the reference's
    new_generator), previous scope restored on exit."""
    from ..nn import layer_base
    global _prefix
    if new_generator is not None and not isinstance(new_generator, str):
        raise TypeError("guard(new_generator) takes a str prefix")
    prev_gen, prev_layer = switch()
    prev_prefix, _prefix = _prefix, (new_generator or "")
    # layer default names pick the prefix up too, so two guards yield
    # disjoint state-dict keys
    layer_base._layer_name_prefix = _prefix
    try:
        yield
    finally:
        global _generate_counters
        _generate_counters = prev_gen
        _prefix = prev_prefix
        layer_base._layer_name_prefix = prev_prefix
        layer_base._layer_name_counters.clear()
        layer_base._layer_name_counters.update(prev_layer)
