"""Per-step collective-communication breakdown from compiled HLO.

PR 3 gave the trainer a host-sync counter and PR 4 a compile counter so
perf contracts could be PROVEN; this is the same discipline for the
communication the overlap schedules (`distributed.overlap`) claim to
hide.  The optimized (post-GSPMD-partitioning) HLO of a compiled step
names every collective XLA will run — all-reduce, all-gather,
reduce-scatter, all-to-all, collective-permute, sync or async-`-start`
form — with its per-device output shape.  Parsing it yields:

- how many collectives one step issues, by kind;
- the per-device bytes they move;
- ``comm_ms``: those bytes over an interconnect-bandwidth model
  (``PADDLE_TPU_ICI_GBPS`` overrides; public per-chip ICI figures
  otherwise; a nominal loopback figure on the host backend) — an
  ESTIMATE of the exposed-serial transfer time, which the trainers
  divide by the measured step time for ``comm_fraction``.

The parse is deterministic and backend-honest (it reads what XLA will
actually execute, not what the Python source asked for), so tests can
assert e.g. "the ZeRO-3 overlap step gathers params with all-gather and
returns grads with reduce-scatter" structurally.
"""
from __future__ import annotations

import os
import re
import warnings
from typing import Dict, Optional

__all__ = ["parse_hlo_collectives", "estimate_comm_ms",
           "estimate_dcn_ms", "analyze_compiled", "analyze_jit",
           "empty_breakdown", "COLLECTIVE_KINDS",
           "axis_groups_from_shape", "mesh_axis_groups"]

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16,
}

# `%all-gather.3 = f32[4,16]{1,0} all-gather(` — capture the result
# shape(s) and the op kind.  Tuple shapes (variadic collectives) may
# carry `/*index=N*/` comments and layout annotations with nested
# parens (`{:T(8,128)}` tiling on TPU), so the tuple match allows one
# paren nesting level.  Async collectives appear as `-start`/`-done`
# pairs; only the start carries the transfer (the done is bookkeeping).
_OP_RE = re.compile(
    r"=\s+(?P<shape>\((?:[^()]|\([^()]*\))*\)|[a-z0-9]+\[[0-9,]*\]\S*)"
    r"\s+(?P<kind>" + "|".join(COLLECTIVE_KINDS) + r")(?P<async>-start)?\(")

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")


def _shape_bytes(shape_text: str, async_start: bool = False,
                 kind: str = "") -> int:
    """Bytes of one HLO shape literal (tuples sum their elements).

    async_start: an async `-start` op's tuple shape is
    (operand, result[, contexts...]) — only the RESULT is wire traffic.
    Context elements (u32[] sync tokens, e.g. the trailing pair of
    collective-permute-start) are dropped by an absolute tiny-size
    filter, then the result is picked by op kind: reduce-scatter's
    result is the SMALLEST data buffer (operand/groupsize — a relative
    filter would misclassify it as context at large group sizes), every
    other kind's result is the largest (gather grows, reduce/permute
    keep the operand size, where max == the result)."""
    sizes = []
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        sizes.append(n * size)
    if async_start and len(sizes) > 1:
        data = [s for s in sizes if s > 8] or sizes
        return min(data) if kind == "reduce-scatter" else max(data)
    return sum(sizes)


# computation header: `%region_0.26_spmd (param: ...) -> ... {` (op
# lines are excluded by the absence of ` = `)
_COMP_RE = re.compile(r"\s*(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_WHILE_RE = re.compile(
    r"condition=%([\w.\-]+).*?body=%([\w.\-]+)|"
    r"body=%([\w.\-]+).*?condition=%([\w.\-]+)")
_CONST_RE = re.compile(r"=\s*[su]\d+\[\]\S*\s+constant\((\d+)\)")


def _while_multipliers(lines_by_comp):
    """comp name -> execution multiplier: a collective inside a
    while-body computation runs once per loop trip (a lax.scan body:
    num_layers trips for the ZeRO-3 layer scan, M+2(pp-1) ticks for
    1F1B), and nested scans multiply.  Trip counts come from the loop
    condition's `i < constant(N)` compare; an unparseable condition
    falls back to 1 (i.e. the old static count — never overcounting)."""
    parent_of = {}   # body comp -> (trip, comp containing the while)
    for comp, lines in lines_by_comp.items():
        for ln in lines:
            if " while(" not in ln:
                continue
            m = _WHILE_RE.search(ln)
            if not m:
                continue
            cond, body = (m.group(1), m.group(2)) if m.group(1) \
                else (m.group(4), m.group(3))
            consts = [int(c) for cl in lines_by_comp.get(cond, [])
                      for c in _CONST_RE.findall(cl)]
            has_cmp = any("compare(" in cl and "direction=L" in cl
                          for cl in lines_by_comp.get(cond, []))
            trip = max(consts) if (consts and has_cmp) else 1
            parent_of[body] = (max(trip, 1), comp)

    def mult(comp, seen=()):
        if comp in seen or comp not in parent_of:
            return 1
        trip, parent = parent_of[comp]
        return trip * mult(parent, seen + (comp,))

    return {comp: mult(comp) for comp in lines_by_comp}


# `replica_groups={{0,1,2,3},{4,5,6,7}}` (explicit) and the iota form
# `replica_groups=[4,2]<=[2,4]T(1,0)` (v2: groups-by-rows of an iota
# reshaped to dims, optionally transposed).
_GROUPS_EXPLICIT_RE = re.compile(r"replica_groups=\{\{(.*?)\}\}")
_GROUPS_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]"
    r"(?:T\(([0-9,]+)\))?")


def _parse_replica_groups(line: str):
    """Device-id groups of one collective line, or None when the op
    carries no/empty replica_groups (= one group of every device)."""
    m = _GROUPS_EXPLICIT_RE.search(line)
    if m:
        groups = []
        for part in m.group(1).split("},{"):
            ids = [int(x) for x in part.replace(" ", "").split(",")
                   if x.lstrip("-").isdigit()]
            if ids:
                groups.append(ids)
        return groups or None
    m = _GROUPS_IOTA_RE.search(line)
    if m:
        n_groups, group_size = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        n = 1
        for d in dims:
            n *= d
        ids = list(range(n))
        if m.group(4):
            import numpy as _np
            perm = [int(x) for x in m.group(4).split(",")]
            ids = _np.arange(n).reshape(dims).transpose(perm) \
                .reshape(-1).tolist()
        if n_groups * group_size == n:
            return [ids[i * group_size:(i + 1) * group_size]
                    for i in range(n_groups)]
    return None


def axis_groups_from_shape(shape) -> Dict[str, list]:
    """Logical-device-id groups per mesh axis from an ORDERED
    ``{axis: size}`` mapping (order must match the mesh's axis order —
    XLA replica groups index the flattened device assignment).  Axes of
    extent 1 are dropped.  This is how a serving-mesh collective gets
    ATTRIBUTED: an all-reduce whose replica groups equal the 'tp'
    groups is tp traffic (the RowParallelLinear partial-sum reduce of a
    tp-sharded decode), one matching 'dp' is data-parallel traffic."""
    import numpy as _np
    names = list(shape)
    dims = [int(shape[a]) for a in names]
    n = 1
    for d in dims:
        n *= d
    idx = _np.arange(n).reshape(dims)
    out: Dict[str, list] = {}
    for i, ax in enumerate(names):
        if dims[i] <= 1:
            continue
        rows = _np.moveaxis(idx, i, -1).reshape(-1, dims[i])
        out[ax] = [frozenset(int(x) for x in r) for r in rows]
    return out


def mesh_axis_groups(mesh) -> Dict[str, list]:
    """axis_groups_from_shape over a live jax Mesh."""
    return axis_groups_from_shape(
        {ax: int(sz) for ax, sz in mesh.shape.items()})


def _match_axis(groups, axis_sets: Dict[str, set], n_dev: int) -> str:
    """Name the mesh axis whose group partition equals this op's
    replica groups; 'all' for a single global group on a multi-axis
    mesh, 'other' for anything unrecognized (merged-axis collectives)."""
    if groups is None:
        gset = {frozenset(range(n_dev))}
    else:
        gset = {frozenset(g) for g in groups}
    for ax, gs in axis_sets.items():
        if gset == gs:
            return ax
    if gset == {frozenset(range(n_dev))}:
        return "all"
    return "other"


def _crosses_slice(groups, slice_size: int) -> bool:
    """True when any replica group spans two DCN slices (device id //
    slice_size).  No groups recorded means one global group — that
    crosses slices whenever the caller asks (slice_size is only passed
    on a multi-slice mesh)."""
    if not groups:
        return True
    for g in groups:
        if len({d // slice_size for d in g}) > 1:
            return True
    return False


def parse_hlo_collectives(hlo_text: str,
                          slice_size: Optional[int] = None,
                          axis_groups: Optional[Dict] = None) -> Dict:
    """Scan optimized HLO for collective ops.

    Returns {"count": int, "bytes": int, "by_op": {kind: {"count", "bytes"}}}
    — bytes are per-device output bytes per STEP: async `-done` ops and
    the tuple-carrying `-start` intermediates are not double counted,
    and a collective inside a while/scan body counts once per loop trip
    (the scanned schedules — ZeRO-3 layer gathers, 1F1B tick ppermutes
    — would otherwise underreport by the trip count).

    slice_size (devices per DCN slice) additionally splits every op's
    bytes into "ici_bytes" (replica groups contained in one slice) vs
    "dcn_bytes" (groups spanning slices — the cross-datacenter-network
    traffic), per kind and as top-level totals: the evidence the
    hierarchical-DP parity phase and the dcn-bound doctor rule read.

    axis_groups (``mesh_axis_groups``/``axis_groups_from_shape``)
    additionally attributes every op to the MESH AXIS whose group
    partition its replica groups equal — the ISSUE 18 tp/dp collective
    split for serving executables — as a top-level ``by_axis``
    {axis: {"count", "bytes"}} breakdown."""
    lines_by_comp: Dict[str, list] = {"": []}
    comp = ""
    for line in hlo_text.splitlines():
        stripped = line.rstrip()
        if stripped.endswith("{") and " = " not in stripped:
            m = _COMP_RE.match(stripped)
            if m:
                comp = m.group(1)
                lines_by_comp.setdefault(comp, [])
                continue
        lines_by_comp.setdefault(comp, []).append(line)
    mults = _while_multipliers(lines_by_comp)

    split = slice_size is not None and slice_size > 0
    attribute = bool(axis_groups)
    if attribute:
        axis_sets = {ax: set(gs) for ax, gs in axis_groups.items()}
        n_dev = max(max(g) for gs in axis_groups.values()
                    for g in gs) + 1
        by_axis: Dict[str, Dict[str, int]] = {}
    by_op = {k: {"count": 0, "bytes": 0} for k in COLLECTIVE_KINDS}
    if split:
        for v in by_op.values():
            v["ici_bytes"] = 0
            v["dcn_bytes"] = 0
    for comp, lines in lines_by_comp.items():
        scale = mults.get(comp, 1)
        for line in lines:
            for m in _OP_RE.finditer(line):
                kind = m.group("kind")
                b = scale * _shape_bytes(
                    m.group("shape"),
                    async_start=bool(m.group("async")), kind=kind)
                by_op[kind]["count"] += scale
                by_op[kind]["bytes"] += b
                if split or attribute:
                    gl = _parse_replica_groups(line)
                if split:
                    cross = _crosses_slice(gl, slice_size)
                    by_op[kind]["dcn_bytes" if cross else "ici_bytes"] += b
                if attribute:
                    slot = by_axis.setdefault(
                        _match_axis(gl, axis_sets, n_dev),
                        {"count": 0, "bytes": 0})
                    slot["count"] += scale
                    slot["bytes"] += b
    total_c = sum(v["count"] for v in by_op.values())
    total_b = sum(v["bytes"] for v in by_op.values())
    out = {"count": total_c, "bytes": total_b,
           "by_op": {k: v for k, v in by_op.items() if v["count"]}}
    if split:
        out["ici_bytes"] = sum(v["ici_bytes"]
                               for v in out["by_op"].values())
        out["dcn_bytes"] = sum(v["dcn_bytes"]
                               for v in out["by_op"].values())
    if attribute:
        out["by_axis"] = by_axis
    return out


# public per-chip ICI bandwidth figures (GB/s, order-of-magnitude — the
# model is for a fraction, not a benchmark); host backend gets a nominal
# shared-memory figure so CPU dryruns report a non-degenerate fraction.
_ICI_GBPS = {
    "v2": 60.0, "v3": 70.0, "v4": 100.0, "v5 lite": 40.0, "v5e": 40.0,
    "v5p": 120.0, "v5": 120.0, "v6 lite": 90.0, "v6e": 90.0,
}
_HOST_GBPS = 8.0


def _bandwidth_gbps(device=None) -> float:
    env = os.environ.get("PADDLE_TPU_ICI_GBPS")
    if env:
        return float(env)
    kind = getattr(device, "device_kind", "").lower() if device else ""
    for key in sorted(_ICI_GBPS, key=len, reverse=True):
        if key in kind:
            return _ICI_GBPS[key]
    return _HOST_GBPS


def estimate_comm_ms(n_bytes: int, device=None) -> float:
    """Transfer-time estimate for `n_bytes` per-device collective bytes
    under the bandwidth model (PADDLE_TPU_ICI_GBPS overrides)."""
    bw = _bandwidth_gbps(device) * 1e9
    return (n_bytes / bw) * 1e3 if bw > 0 else 0.0


# cross-slice (data-center network) bandwidth is roughly an order of
# magnitude below ICI; public multislice figures put per-chip DCN at
# ~25 GB/s — a model for the fraction, not a benchmark.
_DCN_GBPS = 25.0


def estimate_dcn_ms(n_bytes: int) -> float:
    """Transfer-time estimate for `n_bytes` of cross-slice (DCN)
    collective bytes (PADDLE_TPU_DCN_GBPS overrides)."""
    env = os.environ.get("PADDLE_TPU_DCN_GBPS")
    bw = (float(env) if env else _DCN_GBPS) * 1e9
    return (n_bytes / bw) * 1e3 if bw > 0 else 0.0


_warned_degraded = False


def empty_breakdown(error: Optional[str] = None) -> Dict:
    """The shape of "we could not measure": zero collectives, the
    ``unavailable`` flag, and (when known) the error.  Callers that
    stored this report comm_ms 0 with unavailable=True instead of
    crashing mid-training."""
    out = {"count": 0, "bytes": 0, "by_op": {}, "comm_ms": 0.0,
           "unavailable": True}
    if error:
        out["error"] = error
    return out


def _degraded(stage: str, exc: BaseException) -> Dict:
    """Comm stats are DIAGNOSTICS: a backend whose AOT HLO analysis
    raises (no as_text on deserialized executables, exotic runtimes,
    jax internals moving) must degrade the measurement, never the
    training step.  Warn ONCE per process, count every failure in the
    metrics registry, hand back an empty breakdown."""
    global _warned_degraded
    err = f"{type(exc).__name__}: {str(exc)[:200]}"
    try:
        from ..observability import metrics as _metrics
        _metrics.counter("comm_stats_failures_total",
                         "comm-stats AOT analyses that degraded",
                         labels=("stage",)).labels(stage=stage).inc()
    except Exception:
        pass
    if not _warned_degraded:
        _warned_degraded = True
        warnings.warn(
            f"comm_stats: HLO analysis unavailable on this backend "
            f"({stage}: {err}); reporting an empty collective breakdown "
            f"(training unaffected, comm_fraction unmeasured)")
    return empty_breakdown(err)


def analyze_compiled(compiled, device=None,
                     slice_size: Optional[int] = None,
                     axis_groups: Optional[Dict] = None) -> Dict:
    """Collective breakdown + comm_ms estimate of one compiled XLA
    executable (a `jax.stages.Compiled`).  Never raises: a backend
    where ``as_text``/parsing fails yields ``empty_breakdown()`` with a
    warn-once + failure counter instead of propagating mid-training.

    slice_size enables the ici/dcn byte split (see
    parse_hlo_collectives); comm_ms then prices ICI and DCN bytes at
    their own bandwidths instead of pretending the slow tier is ICI.
    axis_groups enables the per-mesh-axis attribution ("by_axis")."""
    try:
        txt = compiled.as_text()
        out = parse_hlo_collectives(txt, slice_size=slice_size,
                                    axis_groups=axis_groups)
        if "dcn_bytes" in out:
            out["comm_ms"] = round(
                estimate_comm_ms(out["ici_bytes"], device)
                + estimate_dcn_ms(out["dcn_bytes"]), 4)
        else:
            out["comm_ms"] = round(
                estimate_comm_ms(out["bytes"], device), 4)
        return out
    except Exception as e:
        return _degraded("analyze_compiled", e)


def analyze_jit(jitfn, *args, device=None,
                slice_size: Optional[int] = None,
                axis_groups: Optional[Dict] = None) -> Optional[Dict]:
    """AOT lower+compile `jitfn` at `args` (values or ShapeDtypeStructs)
    and analyze its collectives.  Returns None when lowering/compiling
    fails (the caller's step still runs; stats just stay unmeasured,
    with a warn-once + failure counter) — comm stats are diagnostics
    and must never take the training step down."""
    try:
        compiled = jitfn.lower(*args).compile()
    except Exception as e:
        _degraded("analyze_jit", e)
        return None
    return analyze_compiled(compiled, device=device,
                            slice_size=slice_size, axis_groups=axis_groups)
