"""Persistent XLA compilation cache wiring.

BENCH_r05 paid a 95.4s warmup+compile on EVERY bench run because nothing
persisted XLA executables across processes.  JAX ships a persistent
compilation cache (``jax_compilation_cache_dir``); this module turns it
on by default for paddle_tpu trainers and the bench:

- ``PADDLE_TPU_COMPILE_CACHE=<dir>`` picks the location;
- ``PADDLE_TPU_COMPILE_CACHE=0`` (or ``off``) disables it;
- unset: ``$XDG_CACHE_HOME/paddle_tpu/xla_cache`` (``~/.cache/...``).

An already-configured cache dir (e.g. the test suite's conftest) is
respected and never overridden.

CPU-backend guard: jaxlib 0.4.x ABORTS (duplicate JIT symbol
registration) when a multi-device SPMD executable is *deserialized* from
the persistent cache on the CPU backend.  Writing those entries is fine
and single-device programs deserialize fine, so the guard serves cache
HITS only for 1-partition/1-replica programs on CPU — the same policy
the test suite has run under since PR 1.  On TPU all programs are
served.  Failures anywhere in this wiring degrade to "no cache", never
to a crashed trainer (the remote-compile retry path must keep working
when the cache backend misbehaves).
"""
from __future__ import annotations

import contextlib
import os
from typing import Optional

__all__ = ["ensure_compile_cache", "compile_cache_dir",
           "compile_cache_enabled", "suspend_cpu_cache_hits"]

_STATE: dict = {"resolved": False, "dir": None}
_SUSPEND = {"depth": 0}
_OFF_VALUES = ("0", "off", "false", "none", "disabled")


@contextlib.contextmanager
def suspend_cpu_cache_hits():
    """While active, the CPU-backend guard refuses ALL persistent-cache
    hits (entries are still WRITTEN, so nothing is lost for later TPU
    runs).  Used when compiling executables with DONATED operands on the
    CPU backend: jaxlib 0.4.x mis-aliases donated buffers in executables
    deserialized from the persistent cache (the hazard PR 2 hit with
    rollback; the serving engine's decode executable donates its KV
    cache the same way) — compiling fresh is the dodge.  No-op on TPU.
    """
    _SUSPEND["depth"] += 1
    try:
        yield
    finally:
        _SUSPEND["depth"] -= 1


def _default_dir() -> str:
    base = os.environ.get("XDG_CACHE_HOME") or \
        os.path.join(os.path.expanduser("~"), ".cache")
    return os.path.join(base, "paddle_tpu", "xla_cache")


def _install_cpu_spmd_guard() -> None:
    """Serve persistent-cache hits on CPU only for single-device
    programs (see module docstring). Idempotent."""
    try:
        from jax._src import compilation_cache as _cc
    except Exception:  # pragma: no cover - jax internals moved
        return
    if getattr(_cc.get_executable_and_time, "_pd_spmd_guard", False):
        return
    orig_get = _cc.get_executable_and_time

    def _guarded_get(cache_key, compile_options, backend):
        try:
            if getattr(backend, "platform", "cpu") == "cpu":
                if _SUSPEND["depth"] > 0:
                    # donated-operand executable being built (serving
                    # engine decode/prefill): deserializing those on CPU
                    # mis-aliases the donation — force a fresh compile
                    return None, None
                ebo = compile_options.executable_build_options
                if ebo.num_partitions > 1 or ebo.num_replicas > 1:
                    return None, None
        except Exception:
            return None, None
        return orig_get(cache_key, compile_options, backend)

    _guarded_get._pd_spmd_guard = True
    _cc.get_executable_and_time = _guarded_get


def ensure_compile_cache() -> Optional[str]:
    """Enable the persistent XLA compile cache (idempotent); returns the
    active cache directory, or None when disabled/unavailable."""
    if _STATE["resolved"]:
        return _STATE["dir"]
    _STATE["resolved"] = True
    env = os.environ.get("PADDLE_TPU_COMPILE_CACHE", "").strip()
    if env.lower() in _OFF_VALUES:
        return None
    try:
        import jax
        current = getattr(jax.config, "jax_compilation_cache_dir", None)
        if current:
            # someone (conftest, user) already configured it: adopt
            _install_cpu_spmd_guard()
            _STATE["dir"] = current
            return current
        path = os.path.abspath(os.path.expanduser(env or _default_dir()))
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # the cache is LIVE from here: record it and install the guard
        # first, so a failure on the tunables below can never leave an
        # active cache without the CPU-SPMD abort guard (or report a
        # live cache as disabled)
        _install_cpu_spmd_guard()
        _STATE["dir"] = path
        try:
            # trainer executables are exactly the entries worth
            # persisting; the default 1s/min-size thresholds would also
            # skip the small eval/update programs, so disable them
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:
            pass  # older jax: thresholds keep their defaults
        return path
    except Exception:
        # cache is an optimization: a read-only FS, an old jax, or a
        # flag rename must never take the trainer down
        _STATE["dir"] = None
        return None


def compile_cache_dir() -> Optional[str]:
    """The active persistent cache dir (after ensure_compile_cache)."""
    return _STATE["dir"]


def compile_cache_enabled() -> bool:
    return _STATE["dir"] is not None
