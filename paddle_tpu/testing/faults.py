"""Deterministic fault injection, env/flag driven.

Each injector reads its PADDLE_FAULT_* variable lazily so subprocesses
(dataloader workers, launched trainers) inherit the fault plan through
the environment, and tests can monkeypatch it per-case. All counters are
process-local and 1-indexed, making every fault reproducible: "the 3rd
`put` fails" means the same call in every run.

Supported faults
----------------
PADDLE_FAULT_FS="op:nth[:count][,op2:nth2...]"
    Fail the nth (.. nth+count-1) invocation of the named filesystem op
    with InjectedFault (an OSError, so the retry/backoff machinery in
    framework/fs.py treats it like a transient HDFS hiccup). `op` is one
    of put/get/exists/mkdir/remove/list/open_read/open_write/run, or "*"
    to match any op (matched against a shared counter).
PADDLE_FAULT_NAN_STEP="k"
    SpmdTrainer poisons every gradient with NaN on train step k
    (1-indexed, compiled in-graph so it works under jit/donation).
PADDLE_FAULT_WORKER_KILL="w:after_n"
    Multiprocess DataLoader worker w calls os._exit(137) after
    producing after_n batches — a SIGKILL-like crash (no close_writer,
    no traceback) that exercises death detection + bounded restart.
PADDLE_FAULT_SIGTERM_STEP="k"
    The training process sends itself SIGTERM right after train step k
    completes — a deterministic preemption for kill-and-resume tests.
PADDLE_FAULT_CKPT_TRUNCATE="n"
    The nth write_checkpoint commit (1-indexed, process-local) writes a
    TRUNCATED state payload, renames the directory into its final name,
    and hard-exits 137 — a mid-commit kill whose partial shard LOOKS
    committed on disk but fails manifest validation.  Exercises the
    resume fallback walk past a corrupt newest checkpoint.
PADDLE_FAULT_MESH_SHRINK="n"
    create_mesh sees only the first n devices — "restore woke up on a
    smaller topology" (the scheduler gave back fewer chips), without
    re-execing under a different XLA device-count flag.
PADDLE_FAULT_FS_DELAY_MS="op:ms[,op2:ms2...]"
    Sleep ms milliseconds before each matching filesystem op ("*"
    matches any) — deterministic slow-storage jitter for checkpoint
    commit / delayed-write tests.  Composes with PADDLE_FAULT_FS.
PADDLE_FAULT_HANG="step:seconds"
    The calling loop stalls (time.sleep) for `seconds` right after
    train step / decode tick number `step` (1-indexed, once per
    process) — a deterministic no-progress hang for the observability
    watchdog's stall-detection tests.  The sleep happens ON the step
    loop's thread, exactly like a wedged collective or a dead remote
    store would.
PADDLE_FAULT_SLICE_DOWN="slice:step"
    The armed DCN slice goes dark from train step `step` on
    (1-indexed): membership-aware beats for that slice are swallowed,
    so the failure detector sees a real growing staleness window,
    declares the slice dead, and the trainer's in-memory mesh reform
    runs — a deterministic whole-slice loss without killing the test
    process.  Multi-host deployments can instead just stop the slice's
    processes; the heartbeat file going stale has the same effect.
PADDLE_FAULT_DCN_DELAY_MS="ms"
    Sleep ms milliseconds inside every DCN collective guard dispatch —
    deterministic slow-DCN jitter for the guard's retry/timeout tests.
    Composes with PADDLE_FAULT_SLICE_DOWN.
"""
from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

__all__ = ["InjectedFault", "maybe_fail_fs", "nan_poison_step",
           "maybe_kill_worker", "maybe_sigterm", "reset",
           "ckpt_truncate_commit", "mesh_shrink", "maybe_delay_fs",
           "maybe_hang", "flightrec_dump", "slice_down", "slice_is_down",
           "maybe_delay_dcn"]


class InjectedFault(IOError):
    """Raised by an armed fault point (subclasses IOError so fs-level
    retry logic treats injected faults like real transient I/O errors).
    """


_lock = threading.Lock()
_fs_counts: dict = {}
_sigterm_fired = False
_ckpt_commits = 0
_hang_fired = False


def reset():
    """Clear all injection counters (tests call this between cases)."""
    global _sigterm_fired, _ckpt_commits, _hang_fired
    with _lock:
        _fs_counts.clear()
        _sigterm_fired = False
        _ckpt_commits = 0
        _hang_fired = False


def flightrec_dump(reason: str):
    """Best-effort flight-recorder bundle before a fault point kills
    the process: the injected death should leave the same black box a
    real one would.  Never raises — a broken dump path must not change
    the fault's semantics."""
    try:
        from ..observability import flightrec
        flightrec.note_event("injected_fault", reason=reason)
        flightrec.dump(reason)
    except Exception:
        pass


def _parse_fs_spec(spec: str):
    """-> list of (op, first, last) windows (1-indexed, inclusive)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) < 2:
            continue
        op = bits[0]
        try:
            first = int(bits[1])
            count = int(bits[2]) if len(bits) > 2 else 1
        except ValueError:
            continue
        out.append((op, first, first + count - 1))
    return out


def maybe_fail_fs(op: str):
    """Fault point for filesystem operations: raises InjectedFault when
    PADDLE_FAULT_FS arms this (op, call-ordinal)."""
    spec = os.environ.get("PADDLE_FAULT_FS")
    if not spec:
        return
    with _lock:
        windows = _parse_fs_spec(spec)
        for w_op, first, last in windows:
            if w_op != op and w_op != "*":
                continue
            key = w_op  # "*" windows share one counter across ops
            n = _fs_counts.get(key, 0) + 1
            _fs_counts[key] = n
            if first <= n <= last:
                raise InjectedFault(
                    f"injected fs fault: op={op!r} call #{n} "
                    f"(PADDLE_FAULT_FS={spec!r})")
            return  # first matching window owns the counter


def nan_poison_step() -> Optional[int]:
    """Step number (1-indexed) whose gradients SpmdTrainer poisons with
    NaN, or None. Read at trainer BUILD time — the poison compiles into
    the step as a jnp.where on the step counter."""
    v = os.environ.get("PADDLE_FAULT_NAN_STEP")
    if not v:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def maybe_kill_worker(worker_id: int, batches_done: int):
    """Fault point inside a dataloader worker process: hard-exit (no
    cleanup, like an OOM SIGKILL) once the armed worker has produced
    `after_n` batches."""
    spec = os.environ.get("PADDLE_FAULT_WORKER_KILL")
    if not spec:
        return
    try:
        w, after_n = (int(x) for x in spec.split(":"))
    except ValueError:
        return
    if worker_id == w and batches_done >= after_n:
        flightrec_dump("worker_kill")
        os._exit(137)


def ckpt_truncate_commit() -> bool:
    """Fault point inside write_checkpoint: True exactly on the armed
    nth commit of this process — the caller then commits a truncated
    payload and hard-exits (see module docstring)."""
    global _ckpt_commits
    v = os.environ.get("PADDLE_FAULT_CKPT_TRUNCATE")
    if not v:
        return False
    try:
        nth = int(v)
    except ValueError:
        return False
    with _lock:
        _ckpt_commits += 1
        return _ckpt_commits == nth


def mesh_shrink() -> Optional[int]:
    """Device-count clamp for create_mesh (PADDLE_FAULT_MESH_SHRINK):
    the mesh is built from only the first n devices, simulating a
    restore onto a smaller surviving topology."""
    v = os.environ.get("PADDLE_FAULT_MESH_SHRINK")
    if not v:
        return None
    try:
        n = int(v)
    except ValueError:
        return None
    return n if n >= 1 else None


def maybe_delay_fs(op: str):
    """Delay point for filesystem operations: sleeps when
    PADDLE_FAULT_FS_DELAY_MS arms this op (deterministic slow-storage
    jitter; the op still succeeds)."""
    spec = os.environ.get("PADDLE_FAULT_FS_DELAY_MS")
    if not spec:
        return
    for part in spec.split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        w_op, _, ms = part.partition(":")
        if w_op != op and w_op != "*":
            continue
        try:
            delay = float(ms)
        except ValueError:
            continue
        if delay > 0:
            time.sleep(delay / 1e3)
        return


def slice_down() -> Optional[tuple]:
    """(slice_id, step) parsed from PADDLE_FAULT_SLICE_DOWN, or None."""
    spec = os.environ.get("PADDLE_FAULT_SLICE_DOWN")
    if not spec or ":" not in spec:
        return None
    sid_s, _, step_s = spec.partition(":")
    try:
        return int(sid_s), int(step_s)
    except ValueError:
        return None


def slice_is_down(slice_id: int, step: int) -> bool:
    """Fault point for slice heartbeats: True when the armed slice must
    stay silent at `step` (silent from the armed step onward, so the
    heartbeat age grows monotonically like a real dead slice's)."""
    armed = slice_down()
    return armed is not None and slice_id == armed[0] and step >= armed[1]


def maybe_delay_dcn():
    """Delay point inside the DCN collective guard's dispatch
    (PADDLE_FAULT_DCN_DELAY_MS): deterministic cross-slice latency; the
    collective still succeeds."""
    v = os.environ.get("PADDLE_FAULT_DCN_DELAY_MS")
    if not v:
        return
    try:
        ms = float(v)
    except ValueError:
        return
    if ms > 0:
        time.sleep(ms / 1e3)


def maybe_sigterm(step: int):
    """Fault point on the training thread: deliver SIGTERM to this
    process right after step k (once per process)."""
    global _sigterm_fired
    v = os.environ.get("PADDLE_FAULT_SIGTERM_STEP")
    if not v or _sigterm_fired:
        return
    try:
        k = int(v)
    except ValueError:
        return
    if step >= k:
        _sigterm_fired = True
        os.kill(os.getpid(), signal.SIGTERM)


def maybe_hang(step: int):
    """Fault point in the step/tick loops (PADDLE_FAULT_HANG=
    "step:seconds"): stall the CALLING thread for `seconds` right
    after step/tick `step` completes, once per process — the
    deterministic no-progress hang the watchdog tests arm."""
    global _hang_fired
    spec = os.environ.get("PADDLE_FAULT_HANG")
    if not spec or _hang_fired:
        return
    k_s, _, secs_s = spec.partition(":")
    try:
        k, secs = int(k_s), float(secs_s)
    except ValueError:
        return
    if step >= k and secs > 0:
        _hang_fired = True
        time.sleep(secs)
