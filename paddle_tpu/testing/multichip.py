"""Shared multichip overlap-parity phases.

One implementation backs the two heavyweight consumers — the driver
dryrun (`__graft_entry__.dryrun_multichip`) and ``bench.py
--multichip-smoke`` — so "the overlapped schedule matches its
synchronous counterpart" is asserted by the same code in both.  The
tier-1 tests (tests/test_overlap_collectives.py) assert the SAME
contract (parity at PARITY_RTOL, zero recompiles, comm fields) but on
deliberately smaller configs — the suite runs close to its time
budget, so they do not reuse these GPT-sized phases; keep the two in
step when the contract changes.

Each phase returns a JSON-able dict:
  {"name", "t_s", "loss_sync": [...], "loss_overlap": [...],
   "max_rel_diff", "comm_ms", "comm_fraction", "comm_by_op",
   "compiles_steps_2plus", ...}
and RAISES (AssertionError) when parity, the recompile-free contract, or
the comm-stats fields are violated — the callers decide whether that
kills a dryrun phase or fails a bench.
"""
from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

__all__ = ["run_zero3_phase", "run_1f1b_phase", "run_moe_a2a_phase",
           "run_elastic_restore_phase", "run_dcn_phase",
           "run_serve_tp_phase", "run_serve_ep_phase", "PARITY_RTOL"]

# fp32 loss parity between a schedule and its synchronous counterpart
PARITY_RTOL = 1e-5


def _assert_comm_fields(stats: dict, who: str):
    for k in ("comm_ms", "comm_fraction", "comm_bytes",
              "comm_collectives"):
        assert stats.get(k) is not None, \
            f"{who}: stats[{k!r}] missing/None (comm breakdown not wired)"


def _parity(sync: List[float], overlap: List[float], who: str) -> float:
    np.testing.assert_allclose(overlap, sync, rtol=PARITY_RTOL,
                               err_msg=f"{who}: overlap schedule diverged "
                               f"from synchronous baseline")
    s, o = np.asarray(sync), np.asarray(overlap)
    return float(np.max(np.abs(o - s) / np.maximum(np.abs(s), 1e-12)))


def run_zero3_phase(steps: int = 3) -> Dict:
    """ZeRO-3 stage: GSPMD-placed gathers (overlap=False) vs the
    shard_map prefetched-gather scan (overlap=True)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    n = len(jax.devices())
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (n, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    def run(overlap):
        paddle.seed(7)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        st = DistributedStrategy()
        st.sharding = True
        st.sharding_configs = {"stage": 3, "overlap": overlap}
        st.recompute_configs = {"scan_layers": True}
        # comm analysis AOT-compiles the step a second time; only the
        # overlap run's stats are asserted on, so only it pays
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=create_mesh({"dp": n}), strategy=st,
                         comm_stats=overlap)
        losses = [float(tr.train_step(ids, labels))]
        snap = compile_counter.snapshot()
        for _ in range(steps - 1):
            losses.append(float(tr.train_step(ids, labels)))
        return losses, snap.new_compiles, tr.stats

    loss_sync, _, _ = run(False)
    loss_ovl, compiles, stats = run(True)
    _assert_comm_fields(stats, "zero3")
    assert compiles == 0, \
        f"zero3 overlap: {compiles} XLA compiles in steps 2..{steps}"
    # the overlapped program must actually gather params and reduce-
    # scatter grads — that IS the ZeRO-3 schedule, assert it structurally
    by_op = stats["comm_by_op"] or {}
    assert by_op.get("all-gather", {}).get("count", 0) > 0, \
        f"zero3 overlap: no all-gather in step HLO ({by_op})"
    assert by_op.get("reduce-scatter", {}).get("count", 0) > 0, \
        f"zero3 overlap: no reduce-scatter in step HLO ({by_op})"
    return {
        "name": "zero3_overlap", "t_s": round(time.perf_counter() - t0, 1),
        "loss_sync": loss_sync, "loss_overlap": loss_ovl,
        "max_rel_diff": _parity(loss_sync, loss_ovl, "zero3"),
        "compiles_steps_2plus": compiles,
        "comm_ms": stats["comm_ms"],
        "comm_fraction": stats["comm_fraction"],
        "comm_by_op": {k: v["count"] for k, v in by_op.items()},
    }


def run_1f1b_phase(steps: int = 3, num_micro: int = 8) -> Dict:
    """Pipeline: GPipe fill/drain vs the 1F1B steady state at pp=2,
    including the structural peak-activation comparison."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import create_mesh
    from paddle_tpu.distributed.pipeline import GPipeTrainer
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.models.gpt import gpt_pipeline_parts
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    n = len(jax.devices())
    pp = 2 if n % 2 == 0 else 1
    dp = n // pp
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    # microbatch rows must divide by dp (the shard_map batch spec)
    ids = rng.randint(0, 64, (num_micro * max(dp, 1), 16)) \
        .astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    def run(schedule):
        paddle.seed(1)
        cfg = GPTConfig(vocab_size=64, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False,
                        tie_word_embeddings=False)
        model = GPTForCausalLM(cfg)
        pre, blocks, post = gpt_pipeline_parts(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        tr = GPipeTrainer(pre, blocks, post, opt,
                          lambda o, l: crit(o, l),
                          mesh=create_mesh({"dp": dp, "pp": pp}),
                          num_microbatches=num_micro, remat=True,
                          schedule=schedule,
                          comm_stats=(schedule == "1f1b"))
        losses = [float(tr.train_step(ids, labels))]
        snap = compile_counter.snapshot()
        for _ in range(steps - 1):
            losses.append(float(tr.train_step(ids, labels)))
        return tr, losses, snap.new_compiles

    tr_g, loss_sync, _ = run("gpipe")
    tr_o, loss_ovl, compiles = run("1f1b")
    stats = tr_o.stats
    _assert_comm_fields(stats, "1f1b")
    assert compiles == 0, \
        f"1f1b: {compiles} XLA compiles in steps 2..{steps}"
    # the acceptance memory claim, asserted structurally: the 1F1B
    # stage-input stash holds at most O(pp) microbatches vs GPipe's M
    slots_o = tr_o.peak_activation_slots()
    slots_g = tr_g.peak_activation_slots()
    assert slots_o <= slots_g, (slots_o, slots_g)
    by_op = stats["comm_by_op"] or {}
    return {
        "name": "1f1b", "t_s": round(time.perf_counter() - t0, 1),
        "pp": pp, "num_micro": num_micro,
        "loss_sync": loss_sync, "loss_overlap": loss_ovl,
        "max_rel_diff": _parity(loss_sync, loss_ovl, "1f1b"),
        "compiles_steps_2plus": compiles,
        "peak_activation_slots": slots_o,
        "peak_activation_slots_gpipe": slots_g,
        "comm_ms": stats["comm_ms"],
        "comm_fraction": stats["comm_fraction"],
        "comm_by_op": {k: v["count"] for k, v in by_op.items()},
    }


def run_elastic_restore_phase(steps: int = 3,
                              extra_steps: int = 2) -> Dict:
    """Elastic shrink restore (ISSUE 10): train on the full dp mesh,
    checkpoint (manifest v2 with the topology record), restore onto
    HALF the devices, and keep training — the resumed loss curve must
    match the uninterrupted full-mesh run, and the restored trainer
    must not recompile after its first (expected, new-mesh) step."""
    import tempfile

    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import (CheckpointManager, SpmdTrainer,
                                        create_mesh)
    from paddle_tpu.distributed.checkpoint import read_manifest
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    n = len(jax.devices())
    shrink = max(n // 2, 1)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(4)
    total = steps + extra_steps
    batches = [rng.randint(0, 128, (n, 32)).astype(np.int32)
               for _ in range(total)]
    labels = [np.roll(b, -1, 1).astype(np.int64) for b in batches]

    def build(dp):
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        return SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                           mesh=create_mesh(
                               {"dp": dp},
                               devices=jax.devices()[:dp]))

    # the uninterrupted reference on the full mesh
    ref = build(n)
    loss_ref = [float(ref.train_step(b, l))
                for b, l in zip(batches, labels)]

    # killed-and-resumed: train `steps`, checkpoint, restore on half
    ckdir = tempfile.mkdtemp(prefix="elastic_ck_")
    tr = build(n)
    loss_pre = [float(tr.train_step(b, l))
                for b, l in zip(batches[:steps], labels[:steps])]
    mgr = CheckpointManager(ckdir, async_save=False)
    path = mgr.save(tr)
    man = read_manifest(path)
    assert man and man.get("version", 1) >= 2 and \
        man.get("mesh_axes") == {"dp": n}, \
        f"manifest topology record missing: {man and man.keys()}"

    tr2 = build(shrink)
    mgr2 = CheckpointManager(ckdir)
    assert mgr2.restore_latest(tr2) is not None
    info = tr2._last_restore_info
    assert info and info["resharded"] and \
        info["mesh_axes"] == {"dp": shrink}, info
    loss_post = [float(tr2.train_step(batches[steps], labels[steps]))]
    snap = compile_counter.snapshot()     # step 1 on the new mesh paid
    for b, l in zip(batches[steps + 1:], labels[steps + 1:]):
        loss_post.append(float(tr2.train_step(b, l)))
    compiles = snap.new_compiles
    assert compiles == 0, \
        f"elastic restore: {compiles} XLA compiles after the first " \
        f"post-restore step"
    resumed = loss_pre + loss_post
    return {
        "name": "elastic_restore",
        "t_s": round(time.perf_counter() - t0, 1),
        "dp_from": n, "dp_to": shrink,
        "manifest_version": man.get("version"),
        "loss_sync": loss_ref, "loss_overlap": resumed,
        "max_rel_diff": _parity(loss_ref, resumed, "elastic_restore"),
        "reshard_restores": mgr2.stats["reshard_restores"],
        "compiles_steps_2plus": compiles,
    }


def run_dcn_phase(steps: int = 3, slices: int = 2) -> Dict:
    """Hierarchical data parallelism (ISSUE 17): flat dp over all
    devices vs a ('dcn', 'dp') mesh — dense all-reduce within a slice
    over ICI, only the cross-slice grad reduce over DCN.  Loss parity
    at PARITY_RTOL, zero recompiles in steps 2+, and the comm split
    must attribute bytes to BOTH tiers (that IS the hierarchy)."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import SpmdTrainer, create_mesh
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    n = len(jax.devices())
    if n % slices != 0 or n // slices < 2:
        slices = 2 if n % 2 == 0 and n >= 4 else 1
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(17)
    ids = rng.randint(0, 128, (n * 2, 32)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int64)

    def run(hier):
        paddle.seed(9)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                    parameters=model.parameters())
        st = DistributedStrategy()
        st.sharding = True
        # ZeRO shards optimizer state over dp WITHIN a slice, so the
        # hierarchical program carries guaranteed intra-slice (ICI)
        # gathers next to the cross-slice (DCN) grad reduce
        st.sharding_configs = {"stage": 3, "overlap": False}
        mesh = create_mesh({"dp": n // slices}, dcn_slices=slices) \
            if hier else create_mesh({"dp": n})
        tr = SpmdTrainer(model, opt, lambda o, l: crit(o, l),
                         mesh=mesh, strategy=st, comm_stats=hier)
        losses = [float(tr.train_step(ids, labels))]
        snap = compile_counter.snapshot()
        for _ in range(steps - 1):
            losses.append(float(tr.train_step(ids, labels)))
        return losses, snap.new_compiles, tr.stats

    loss_flat, _, _ = run(False)
    loss_hier, compiles, stats = run(True)
    _assert_comm_fields(stats, "dcn")
    assert compiles == 0, \
        f"dcn hierarchical: {compiles} XLA compiles in steps 2..{steps}"
    assert stats.get("dcn_slices") == slices, \
        f"dcn: expected {slices} slices in stats, {stats.get('dcn_slices')}"
    ici, dcn = stats.get("comm_bytes_ici"), stats.get("comm_bytes_dcn")
    if slices > 1:
        assert ici and ici > 0, f"dcn: no ICI bytes attributed ({ici})"
        assert dcn and dcn > 0, f"dcn: no DCN bytes attributed ({dcn})"
    by_op = stats["comm_by_op"] or {}
    return {
        "name": "dcn_hierarchical",
        "t_s": round(time.perf_counter() - t0, 1),
        "dcn_slices": slices, "dp_per_slice": n // max(slices, 1),
        "loss_sync": loss_flat, "loss_overlap": loss_hier,
        "max_rel_diff": _parity(loss_flat, loss_hier, "dcn"),
        "compiles_steps_2plus": compiles,
        "comm_ms": stats["comm_ms"],
        "comm_fraction": stats["comm_fraction"],
        "comm_bytes_ici": ici, "comm_bytes_dcn": dcn,
        "comm_by_op": {k: v["count"] for k, v in by_op.items()},
    }


def run_moe_a2a_phase(chunks: int = 2) -> Dict:
    """MoE dispatch/combine: monolithic all-to-all vs K-chunked —
    bitwise-equal outputs, and the chunked program must carry K times
    the collective count."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.distributed import create_mesh
    from paddle_tpu.distributed.mesh import PartitionSpec as P, shard_map
    from paddle_tpu.distributed.moe import MoELayer
    from paddle_tpu.utils import comm_stats as _cs

    t0 = time.perf_counter()
    n = len(jax.devices())
    H, Fd = 8, 16
    paddle.seed(3)
    layer = MoELayer(H, Fd, num_experts=n, top_k=2, capacity_factor=4.0)
    rng = np.random.RandomState(3)
    x = rng.randn(n, 8, H).astype(np.float32)
    mesh = create_mesh({"ep": n})
    args = (jnp.asarray(x), layer.gate.data, layer.experts.w_up.data,
            layer.experts.b_up.data, layer.experts.w_down.data,
            layer.experts.b_down.data)

    def make(k):
        def fn(xs, gate, wu, bu, wd, bd):
            # bind the chunk count at TRACE time (jit defers tracing, so
            # setting it outside would race between the two programs)
            layer.a2a_chunks = k
            y, aux, zl = layer._fn_shard_map(xs, gate, wu, bu, wd, bd)
            return y
        return jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(P("ep"), P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=P("ep")))

    f_mono, f_chunk = make(1), make(chunks)
    comm_mono = _cs.analyze_jit(f_mono, *args)
    comm_chunk = _cs.analyze_jit(f_chunk, *args)
    out_mono = np.asarray(f_mono(*args))
    out_chunk = np.asarray(f_chunk(*args))
    np.testing.assert_array_equal(
        out_chunk, out_mono,
        err_msg="chunked MoE a2a is not bitwise-equal to monolithic")
    # recompile-free contract (steps 2..N) + comm_fraction, same as the
    # other schedules: re-run the chunked program and time it
    from paddle_tpu.utils import compile_counter
    snap = compile_counter.snapshot()
    steps = 3
    t1 = time.perf_counter()
    for _ in range(steps):
        f_chunk(*args).block_until_ready()
    mean_ms = (time.perf_counter() - t1) * 1e3 / steps
    compiles = snap.new_compiles
    assert compiles == 0, \
        f"chunked MoE a2a: {compiles} XLA compiles in steps 2..N"
    a2a_mono = comm_mono["by_op"].get("all-to-all", {}).get("count", 0) \
        if comm_mono else 0
    a2a_chunk = comm_chunk["by_op"].get("all-to-all", {}).get("count", 0) \
        if comm_chunk else 0
    # XLA may decompose one lax.all_to_all into several HLO ops, so the
    # invariant is proportionality: K chunks issue K times the exchanges
    # of the monolithic program (dispatch + combine each)
    assert a2a_mono >= 2, f"monolithic MoE: expected >=2 a2a, {a2a_mono}"
    assert a2a_chunk == chunks * a2a_mono, \
        f"chunked MoE: expected {chunks}x{a2a_mono} a2a, {a2a_chunk}"
    comm_ms = comm_chunk["comm_ms"] if comm_chunk else None
    return {
        "name": "moe_a2a_chunked",
        "t_s": round(time.perf_counter() - t0, 1),
        "chunks": chunks, "a2a_count_mono": a2a_mono,
        "a2a_count_chunked": a2a_chunk,
        "comm_ms": comm_ms,
        "comm_fraction": round(comm_ms / mean_ms, 4)
        if (comm_ms is not None and mean_ms > 0) else None,
        "compiles_steps_2plus": compiles,
        "max_abs_diff": 0.0,
    }


def run_serve_tp_phase(gen_tokens: int = 8) -> Dict:
    """Pod-scale serving (ISSUE 18): a tp=2 serving mesh must generate
    TOKEN-IDENTICAL output to the unsharded engine on BOTH KV layouts,
    the decode loop must stay recompile-free after warmup with sharded
    weights/cache, and the executable observatory entries must record
    the submesh + tp degree they compiled against."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import create_mesh
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import exec_registry
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    assert len(jax.devices()) >= 2, \
        f"serve_tp phase needs >=2 devices, found {len(jax.devices())}"
    # vocab/heads divisible by tp=2 so the embedding and KV heads
    # actually SHARD (non-divisible dims degrade to replicated)
    cfg = GPTConfig(vocab_size=96, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, (n,)).astype(np.int32)
               for n in (5, 7, 6)]

    def run(layout, tp):
        mesh = create_mesh({"dp": 1, "tp": tp}) if tp > 1 else None
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        kw = dict(batch_slots=2, prefill_buckets=[16], mesh=mesh,
                  kv_layout=layout)
        if layout == "paged":
            kw.update(kv_block_size=8, kv_num_blocks=24)
        eng = InferenceEngine(m, **kw)
        eng.warmup(buckets=[16])
        snap = compile_counter.snapshot()
        rids = [eng.add_request(p, max_new_tokens=gen_tokens)
                for p in prompts]
        toks = eng.run()
        return ([list(map(int, toks[r])) for r in rids],
                snap.new_compiles, eng)

    out: Dict = {"name": "serve_tp", "layouts": {}}
    for layout in ("dense", "paged"):
        base, _, _ = run(layout, 1)
        tok2, compiles, eng = run(layout, 2)
        assert tok2 == base, (
            f"serve tp=2 ({layout}): tokens diverged from tp=1\n"
            f"  tp=1: {base}\n  tp=2: {tok2}")
        assert compiles == 0, (
            f"serve tp=2 ({layout}): {compiles} XLA compiles after "
            f"warmup (decode is not shape-stable under tp)")
        metas = [e.meta for e in
                 exec_registry.registry().entries(eng._exec_component)
                 if e.meta.get("submesh")]
        assert metas, \
            f"serve tp=2 ({layout}): no exec entries carry submesh meta"
        for meta in metas:
            assert meta.get("tp") == 2, f"tp meta wrong: {meta}"
            assert meta["submesh"]["shape"].get("tp") == 2, \
                f"submesh shape wrong: {meta}"
            assert len(meta["submesh"]["devices"]) == 2, \
                f"submesh devices wrong: {meta}"
        out["layouts"][layout] = {
            "tokens": sum(len(t) for t in tok2),
            "compiles_after_warmup": compiles,
            "exec_entries_with_submesh": len(metas),
        }
    out["t_s"] = round(time.perf_counter() - t0, 1)
    return out


def run_serve_ep_phase(gen_tokens: int = 8) -> Dict:
    """Expert-parallel MoE serving (ISSUE 19): an ep=2 serving mesh
    must generate TOKEN-IDENTICAL output to the replicated ep=1 MoE
    engine on BOTH KV layouts (the capacity a2a dispatch is an exact
    reformulation of the dense one-hot combine, not an approximation),
    stay recompile-free after warmup, halve the per-device expert-FFN
    residency, carry 'ep' in the exec-registry meta, and attribute the
    dispatch/combine all-to-all bytes to the ep axis in the collective
    fold."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import create_mesh
    from paddle_tpu.inference import InferenceEngine
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.observability import exec_registry
    from paddle_tpu.utils import compile_counter

    t0 = time.perf_counter()
    assert len(jax.devices()) >= 2, \
        f"serve_ep phase needs >=2 devices, found {len(jax.devices())}"
    cfg = GPTConfig(vocab_size=96, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64,
                    use_flash_attention=False,
                    moe_num_experts=4, moe_top_k=2)
    rng = np.random.RandomState(0)
    prompts = [rng.randint(1, 96, (n,)).astype(np.int32)
               for n in (5, 7, 6)]

    def run(layout, ep):
        mesh = create_mesh({"dp": 1, "tp": 1, "ep": ep}) \
            if ep > 1 else None
        paddle.seed(0)
        m = GPTForCausalLM(cfg)
        kw = dict(batch_slots=2, prefill_buckets=[16], mesh=mesh,
                  kv_layout=layout)
        if layout == "paged":
            kw.update(kv_block_size=8, kv_num_blocks=24)
        eng = InferenceEngine(m, **kw)
        eng.warmup(buckets=[16])
        snap = compile_counter.snapshot()
        rids = [eng.add_request(p, max_new_tokens=gen_tokens)
                for p in prompts]
        toks = eng.run()
        return ([list(map(int, toks[r])) for r in rids],
                snap.new_compiles, eng)

    out: Dict = {"name": "serve_ep", "layouts": {}}
    for layout in ("dense", "paged"):
        base, _, eng1 = run(layout, 1)
        tok2, compiles, eng = run(layout, 2)
        assert tok2 == base, (
            f"serve ep=2 ({layout}): tokens diverged from ep=1\n"
            f"  ep=1: {base}\n  ep=2: {tok2}")
        assert compiles == 0, (
            f"serve ep=2 ({layout}): {compiles} XLA compiles after "
            f"warmup (the capacity a2a dispatch is not shape-stable)")
        s1, s2 = eng1.stats, eng.stats
        assert s2["ep"] == 2 and s2["moe_num_experts"] == 4
        assert s2["moe_expert_load"] == s1["moe_expert_load"], (
            f"serve ep=2 ({layout}): expert load histogram diverged\n"
            f"  ep=1: {s1['moe_expert_load']}\n"
            f"  ep=2: {s2['moe_expert_load']}")
        # per-device expert-FFN residency must drop ~ep× vs replicated
        b1 = eng1._moe_expert_bytes_per_device()
        b2 = eng._moe_expert_bytes_per_device()
        assert b2 * 2 == b1, \
            f"expert bytes/device not halved under ep=2: {b1} -> {b2}"
        metas = [e.meta for e in
                 exec_registry.registry().entries(eng._exec_component)
                 if e.meta.get("submesh")]
        assert metas, \
            f"serve ep=2 ({layout}): no exec entries carry submesh meta"
        for meta in metas:
            assert meta.get("ep") == 2, f"ep meta wrong: {meta}"
            assert meta["submesh"]["shape"].get("ep") == 2, \
                f"submesh shape wrong: {meta}"
        # the collective fold must attribute the MoE dispatch/combine
        # all-to-all to the 'ep' axis on the decode executable
        reg = exec_registry.registry()
        reg.analyze_all(eng._exec_component)
        rows = [r for r in reg.snapshot(
                    eng._exec_component)["executables"]
                if r["kind"] == "decode" and r["analyzed"]]
        assert rows, f"serve ep=2 ({layout}): no analyzed decode rows"
        ep_colls = [r for r in rows
                    if (r.get("collectives") or {})
                    .get("by_axis", {}).get("ep", {}).get("count", 0)]
        assert ep_colls, (
            f"serve ep=2 ({layout}): no decode executable attributes "
            f"collective bytes to the ep axis")
        out["layouts"][layout] = {
            "tokens": sum(len(t) for t in tok2),
            "compiles_after_warmup": compiles,
            "expert_bytes_per_device": b2,
            "moe_dropped_rate": s2["moe_dropped_rate"],
            "exec_entries_with_submesh": len(metas),
        }
    out["t_s"] = round(time.perf_counter() - t0, 1)
    return out
