"""Test/chaos utilities — deterministic fault injection for the
resilience stack (distributed/resilience.py).

Production code calls the `faults` hooks at well-defined fault points
(filesystem ops, gradient computation, dataloader workers, the train
step); the hooks are no-ops unless the matching PADDLE_FAULT_* env var
is set, so the hot path pays one cached env lookup.
"""
from . import faults  # noqa: F401
from .faults import InjectedFault  # noqa: F401

__all__ = ["faults", "InjectedFault"]
