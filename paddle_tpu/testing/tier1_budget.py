"""Tier-1 wall-budget guard (ISSUE 19 satellite).

The tier-1 suite runs under one hard wall budget (ROADMAP: 870s for
``pytest -m 'not slow'``).  Every PR adds tests, and the historical
failure mode is silent: a new test file's fast lane costs 90s, nobody
notices, and three PRs later the suite times out under ``timeout -k``
mid-file.  This module keeps the budget honest with three small
pieces:

* a PURE decision function — :func:`files_over_budget` — that maps
  ``{test file: fast-lane seconds}`` to the offenders over the
  per-file budget (``PADDLE_TPU_TIER1_FILE_BUDGET_S``, default 60s),
  minus explicit exemptions (``PADDLE_TPU_TIER1_EXEMPT``, comma list);
* a recorded-durations file (``tests/.tier1_durations.json``) that the
  opt-in conftest hook (``PADDLE_TPU_TIER1_AUTOSPLIT=1``) writes after
  a suite run and reads at collection: a file recorded OVER budget has
  its unmarked tests auto-promoted to the slow lane on the next run —
  the suite self-heals instead of timing out;
* :func:`check_recorded_durations`, the ``bench.py --smoke`` phase:
  fail the smoke when the recorded split has drifted over budget, so
  the drift is a red bench before it is a timed-out CI lane.

Everything here is stdlib-only and import-safe under any backend.
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

__all__ = ["files_over_budget", "load_durations", "record_durations",
           "check_recorded_durations", "durations_path",
           "DEFAULT_FILE_BUDGET_S"]

DEFAULT_FILE_BUDGET_S = 60.0
DURATIONS_BASENAME = ".tier1_durations.json"


def _budget_s() -> float:
    try:
        return float(os.environ.get("PADDLE_TPU_TIER1_FILE_BUDGET_S",
                                    DEFAULT_FILE_BUDGET_S))
    except ValueError:
        return DEFAULT_FILE_BUDGET_S


def _exempt() -> List[str]:
    raw = os.environ.get("PADDLE_TPU_TIER1_EXEMPT", "")
    return [p.strip() for p in raw.split(",") if p.strip()]


def files_over_budget(durations: Dict[str, float],
                      budget_s: Optional[float] = None,
                      exempt: Optional[List[str]] = None
                      ) -> List[Tuple[str, float]]:
    """The decision function, pure so tests can drive it directly:
    which files' recorded FAST-LANE (non-slow) wall time exceeds the
    per-file budget?  ``exempt`` entries match by basename or exact
    path.  Returns ``[(file, seconds), ...]`` sorted worst-first."""
    budget = _budget_s() if budget_s is None else float(budget_s)
    exempt = _exempt() if exempt is None else list(exempt)

    def _exempted(f: str) -> bool:
        base = os.path.basename(f)
        return f in exempt or base in exempt

    out = [(f, float(s)) for f, s in durations.items()
           if isinstance(s, (int, float)) and float(s) > budget
           and not _exempted(f)]
    out.sort(key=lambda fs: -fs[1])
    return out


def durations_path(tests_dir: Optional[str] = None) -> str:
    """Default location: ``tests/.tier1_durations.json`` next to this
    repo's suite (the conftest passes its own directory)."""
    if tests_dir is None:
        tests_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tests")
    return os.path.join(tests_dir, DURATIONS_BASENAME)


def load_durations(path: Optional[str] = None) -> Optional[Dict[str, float]]:
    """The recorded per-file fast-lane durations, or None when no run
    has recorded them yet (a fresh clone must not fail anything)."""
    path = path or durations_path()
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return None
    files = data.get("files") if isinstance(data, dict) else None
    if not isinstance(files, dict):
        return None
    return {str(k): float(v) for k, v in files.items()
            if isinstance(v, (int, float))}


def record_durations(durations: Dict[str, float],
                     path: Optional[str] = None) -> str:
    """Persist one run's per-file fast-lane durations (overwrites —
    the file describes the LAST recorded run, not a rolling mean)."""
    path = path or durations_path()
    payload = {"budget_s": _budget_s(),
               "files": {k: round(float(v), 3)
                         for k, v in sorted(durations.items())}}
    with open(path, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def check_recorded_durations(path: Optional[str] = None
                             ) -> Optional[dict]:
    """The bench --smoke verdict: ``None`` when nothing is recorded,
    else ``{"budget_s", "files", "over_budget": [(file, s), ...]}``."""
    durations = load_durations(path)
    if durations is None:
        return None
    return {"budget_s": _budget_s(), "files": len(durations),
            "over_budget": files_over_budget(durations)}
