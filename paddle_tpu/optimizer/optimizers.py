"""Concrete optimizers.

Reference parity: C++ kernels /root/reference/paddle/fluid/operators/
optimizers/{sgd_op,momentum_op,adam_op,adamax_op,adagrad_op,rmsprop_op,
lamb_op,lars_momentum_op}.cc(.cu) and python/paddle/optimizer/*.py. Each
update rule is a handful of jnp expressions — XLA fuses the whole
parameter update into one kernel per (dtype,shape) bucket, which is what
the reference needed hand-fused `fused_adam`-style ops for.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adagrad", "RMSProp", "Adadelta", "Adam",
           "AdamW", "Adamax", "Lamb", "Lars"]


class SGD(Optimizer):
    """reference sgd_op.cc."""

    def _update(self, p, g, state, lr, step):
        return p - lr * g, state

    def _update_sparse(self, p, g, state, lr, step):
        """Sparse branch of sgd_op.h: scatter-subtract the touched rows
        only (identical numerics to dense — untouched rows have zero
        grad).  Out-of-range rows (merge() padding) are dropped."""
        return p.at[g.rows].add(
            (-lr * g.values).astype(p.dtype), mode="drop"), state


class Momentum(Optimizer):
    """reference momentum_op (use_nesterov attr)."""

    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _update(self, p, g, state, lr, step):
        v = self._momentum * state["velocity"] + g
        if self._use_nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    """reference adagrad_op.cc."""

    _accum_names = ("moment",)

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_val = initial_accumulator_value

    def _init_accumulators(self, param):
        return {"moment": jnp.full_like(param, self._init_val)}

    def _update(self, p, g, state, lr, step):
        m = state["moment"] + g * g
        return p - lr * g / (jnp.sqrt(m) + self._epsilon), {"moment": m}


class RMSProp(Optimizer):
    """reference rmsprop_op.cc (centered option)."""

    _accum_names = ("mean_square", "mean_grad", "momentum")

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update(self, p, g, state, lr, step):
        ms = self._rho * state["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = state["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g / denom
        return p - mom, {"mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adadelta(Optimizer):
    """reference adadelta_op.cc."""

    _accum_names = ("avg_squared_grad", "avg_squared_update")

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def _update(self, p, g, state, lr, step):
        asg = self._rho * state["avg_squared_grad"] + (1 - self._rho) * g * g
        upd = g * jnp.sqrt(state["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * state["avg_squared_update"] + \
            (1 - self._rho) * upd * upd
        return p - lr * upd, {"avg_squared_grad": asg,
                              "avg_squared_update": asu}


class Adam(Optimizer):
    """reference adam_op.cc (AdamFunctor: bias-corrected moments; the
    reference keeps beta pows as accumulators — here step is the counter)."""

    _accum_names = ("moment1", "moment2")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = lazy_mode

    def _update(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        bc1 = 1.0 - self._beta1 ** step
        bc2 = 1.0 - self._beta2 ** step
        step_size = lr * jnp.sqrt(bc2) / bc1
        new_p = p.astype(jnp.float32) - step_size * m1 / (
            jnp.sqrt(m2) + self._epsilon)
        new_p = self._extra_decay(new_p, p, lr)
        return new_p, {"moment1": m1, "moment2": m2}

    def _extra_decay(self, new_p, p, lr):
        return new_p

    def _update_sparse(self, p, g, state, lr, step):
        """SparseAdamFunctor (reference adam_op.h): lazy_mode touches
        only the looked-up rows — moments and params of untouched rows
        stay frozen, an O(n_rows · dim) step instead of O(vocab · dim).
        Non-lazy matches the dense rule exactly (moments decay
        everywhere), implemented by densifying the grad."""
        if not self._lazy_mode:
            return self._update(p, g.to_dense(), state, lr, step)
        r = g.rows
        gv = g.values.astype(jnp.float32)
        m1, m2 = state["moment1"], state["moment2"]
        # out-of-range rows (merge() padding) gather clamped garbage and
        # the matching writes are dropped below, so the result is exact
        m1r = self._beta1 * m1[r] + (1 - self._beta1) * gv
        m2r = self._beta2 * m2[r] + (1 - self._beta2) * gv * gv
        bc1 = 1.0 - self._beta1 ** step
        bc2 = 1.0 - self._beta2 ** step
        step_size = lr * jnp.sqrt(bc2) / bc1
        pr = p[r].astype(jnp.float32) - step_size * m1r / (
            jnp.sqrt(m2r) + self._epsilon)
        pr = self._extra_decay(pr, p[r], lr)  # AdamW: rows decay lazily
        new_p = p.at[r].set(pr.astype(p.dtype), mode="drop")
        return new_p, {"moment1": m1.at[r].set(m1r, mode="drop"),
                       "moment2": m2.at[r].set(m2r, mode="drop")}


class AdamW(Adam):
    """reference adamw logic (python/paddle/optimizer/adamw.py):
    decoupled weight decay p -= lr * coeff * p."""

    _decoupled_wd = 1.0

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode=lazy_mode)
        from ..regularizer import L2Decay
        if isinstance(weight_decay, (int, float)):
            self._wd_coeff = float(weight_decay)
        elif isinstance(weight_decay, L2Decay):
            # decoupled decay is L2-shaped by definition; honor the coeff
            self._wd_coeff = float(weight_decay.coeff)
        else:
            raise TypeError(
                f"AdamW weight_decay must be a float or L2Decay, got "
                f"{type(weight_decay)}")
        self._apply_decay_param_fun = apply_decay_param_fun

    def _extra_decay(self, new_p, p, lr):
        fn = self._apply_decay_param_fun
        if fn is not None and self._cur_param_name is not None and \
                not fn(self._cur_param_name):
            return new_p
        return new_p - lr * self._wd_coeff * p.astype(jnp.float32)


class Adamax(Optimizer):
    """reference adamax_op.cc."""

    _accum_names = ("moment", "inf_norm")

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update(self, p, g, state, lr, step):
        m = self._beta1 * state["moment"] + (1 - self._beta1) * g
        inf = jnp.maximum(self._beta2 * state["inf_norm"], jnp.abs(g))
        lr_t = lr / (1 - self._beta1 ** step)
        return p - lr_t * m / (inf + self._epsilon), \
            {"moment": m, "inf_norm": inf}


class Lamb(Optimizer):
    """reference lamb_op.cc: layer-adaptive Adam with trust ratio."""

    _accum_names = ("moment1", "moment2")
    _decoupled_wd = 1.0

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update(self, p, g, state, lr, step):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m1 = self._beta1 * state["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * state["moment2"] + (1 - self._beta2) * g32 * g32
        m1_hat = m1 / (1 - self._beta1 ** step)
        m2_hat = m2 / (1 - self._beta2 ** step)
        wd = self._wd
        if self._exclude_fn is not None:
            # the hook receives a param-like object carrying .name in BOTH
            # paths (eager: the Parameter; functional: a named stub), so
            # one callback works under eager and compiled training
            if self._cur_param is not None:
                target = self._cur_param
            elif self._cur_param_name is not None:
                import types
                target = types.SimpleNamespace(name=self._cur_param_name)
            else:
                target = None
            if target is not None and self._exclude_fn(target):
                wd = 0.0
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon) + wd * p32
        p_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        return p32 - lr * trust * r, {"moment1": m1, "moment2": m2}


class Lars(Optimizer):
    """reference lars_momentum_op.cu (LARS: layer-wise adaptive rate
    scaling for large-batch SGD)."""

    _accum_names = ("velocity",)

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._wd = lars_weight_decay

    def _update(self, p, g, state, lr, step):
        p_norm = jnp.linalg.norm(p.astype(jnp.float32))
        g_norm = jnp.linalg.norm(g.astype(jnp.float32))
        local_lr = jnp.where(
            (p_norm > 0) & (g_norm > 0),
            self._lars_coeff * p_norm / (g_norm + self._wd * p_norm + 1e-12),
            1.0)
        v = self._momentum * state["velocity"] + \
            lr * local_lr * (g + self._wd * p)
        return p - v, {"velocity": v}
