"""ExponentialMovingAverage of parameters.

Reference: /root/reference/python/paddle/fluid/optimizer.py:3466
(ExponentialMovingAverage): EMA_t = decay * EMA_{t-1} + (1-decay) * p_t,
bias-corrected at apply() time by 1 / (1 - prod of decays) (equals
1 - decay^t for a constant decay), with the optional thres_steps
schedule decay_t = min(decay, (1 + t) / (10 + t)).

TPU-native shape: `update_state` is a pure pytree function usable inside
a jitted train step; the stateful update()/apply()/restore() surface
matches the reference's dygraph usage.
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ExponentialMovingAverage"]


class ExponentialMovingAverage:
    def __init__(self, decay: float = 0.999, thres_steps: bool = False,
                 parameters=None, name=None):
        if not 0.0 <= decay < 1.0:
            raise ValueError(f"decay must be in [0, 1), got {decay}")
        self._decay = float(decay)
        # reference thres_steps is a Variable holding the global step; a
        # boolean flag is the natural eager form (True = schedule on the
        # EMA's own update count)
        self._thres_steps = bool(thres_steps)
        self._parameters = list(parameters) if parameters is not None \
            else None
        self._shadow: Dict[str, jax.Array] = {}
        self._decay_prod: Dict[str, jax.Array] = {}
        self._t = 0
        self._restore_values: Optional[dict] = None

    def _current_decay(self, t):
        if not self._thres_steps:
            return jnp.asarray(self._decay, jnp.float32)
        sched = jnp.asarray((1.0 + t) / (10.0 + t), jnp.float32)
        return jnp.minimum(jnp.asarray(self._decay, jnp.float32), sched)

    # ---- pure functional form (compiled steps) ------------------------
    def init_state(self, params):
        """params pytree -> {'shadow': zeros-like pytree,
        'decay_prod': ones-like scalars, 't': 0}."""
        return {
            "shadow": jax.tree_util.tree_map(
                lambda a: jnp.zeros(a.shape, jnp.float32), params),
            "decay_prod": jnp.ones((), jnp.float32),
            "t": jnp.zeros((), jnp.int32),
        }

    def update_state(self, params, state):
        """One EMA step over a params pytree — pure, jit-safe."""
        t = state["t"] + 1
        d = self._current_decay(t.astype(jnp.float32))
        shadow = jax.tree_util.tree_map(
            lambda s, p: d * s + (1.0 - d) * p.astype(jnp.float32),
            state["shadow"], params)
        return {"shadow": shadow,
                "decay_prod": state["decay_prod"] * d,
                "t": t.astype(jnp.int32)}

    def averaged(self, params, state):
        """Bias-corrected EMA values: shadow / (1 - prod(decay))."""
        corr = jnp.maximum(1.0 - state["decay_prod"], 1e-12)
        return jax.tree_util.tree_map(
            lambda s, p: (s / corr).astype(p.dtype), state["shadow"],
            params)

    # ---- eager surface (reference dygraph usage) ----------------------
    def update(self):
        if self._parameters is None:
            raise RuntimeError(
                "ExponentialMovingAverage constructed without parameters; "
                "pass parameters=model.parameters() for eager use")
        self._t += 1
        d = self._current_decay(float(self._t))
        for p in self._parameters:
            s = self._shadow.get(p.name)
            if s is None:
                s = jnp.zeros(p.data.shape, jnp.float32)
                self._decay_prod[p.name] = jnp.ones((), jnp.float32)
            self._shadow[p.name] = \
                d * s + (1.0 - d) * p.data.astype(jnp.float32)
            self._decay_prod[p.name] = self._decay_prod[p.name] * d

    @contextmanager
    def apply(self, need_restore: bool = True):
        if self._restore_values is not None:
            raise RuntimeError("EMA.apply() calls cannot nest")
        self._restore_values = {}
        for p in self._parameters or []:
            s = self._shadow.get(p.name)
            if s is None:
                continue
            self._restore_values[p.name] = p.data
            corr = jnp.maximum(1.0 - self._decay_prod[p.name], 1e-12)
            p._data = (s / corr).astype(p.data.dtype)
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self):
        if self._restore_values is None:
            return
        for p in self._parameters or []:
            if p.name in self._restore_values:
                p._data = self._restore_values[p.name]
        self._restore_values = None

    def state_dict(self):
        sd = {f"{n}@ema": Tensor(a) for n, a in self._shadow.items()}
        sd.update({f"{n}@decay_prod": Tensor(a)
                   for n, a in self._decay_prod.items()})
        sd["@t"] = self._t
        return sd

    def set_state_dict(self, sd):
        self._t = int(sd.get("@t", 0))
        for key, val in sd.items():
            if key == "@t":
                continue
            arr = val.data if isinstance(val, Tensor) else jnp.asarray(val)
            name, kind = key.rsplit("@", 1)
            if kind == "ema":
                self._shadow[name] = arr
            elif kind == "decay_prod":
                self._decay_prod[name] = arr
