"""Optimizer base.

TPU-native re-design of the reference optimizer stack
(/root/reference/python/paddle/optimizer/optimizer.py and the C++ kernels
under paddle/fluid/operators/optimizers/). The reference appends per-param
update ops (sgd_op.cc, adam_op.cc, ...) into a program; here every
optimizer defines ONE pure update rule

    update(param, grad, state, lr) -> (new_param, new_state)

used two ways:
- eagerly by `step()` (dygraph parity: accumulators live on the optimizer
  keyed by param name, like the reference's `param@accumulator` Scope vars)
- functionally by compiled trainers: `init_state(params)` +
  `apply_gradients(params, grads, state, lr)` over pytrees of jax.Arrays,
  which is what jit/pjit train steps call (state sharding specs follow
  param sharding — that is ZeRO-friendly by construction).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from ..core.errors import InvalidArgumentError
from ..core.tensor import Parameter, Tensor
from ..nn.clip import ClipGradBase
from ..regularizer import L1Decay, L2Decay


def _path_to_name(path) -> str:
    """Join a jax pytree key path into a dotted name ('block.fc.weight').
    Used so name-based decay hooks see readable structured names in the
    functional/compiled path (the eager path passes Parameter.name)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return ".".join(parts)


class Optimizer:
    _accum_names: Sequence[str] = ()

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        from . import lr as lr_mod

        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, float):
            self._weight_decay = L2Decay(weight_decay)
        else:
            self._weight_decay = weight_decay
        # state: param name -> dict of accumulator arrays
        self._accumulators: Dict[str, Dict[str, jax.Array]] = {}
        self._step_count = 0
        # current-param context for per-param decay hooks (AdamW
        # apply_decay_param_fun, Lamb exclude_from_weight_decay_fn)
        self._cur_param_name: Optional[str] = None
        self._cur_param = None
        # compiled trainers install these so hooks see the SAME
        # Parameter.name (and object) in the functional path as in eager
        self._param_name_map: Optional[Dict[str, str]] = None
        self._param_obj_map: Optional[Dict[str, object]] = None
        self._lr_scheduler = self._lr if isinstance(
            self._lr, lr_mod.LRScheduler) else None

    # ---- learning rate ----------------------------------------------------
    def get_lr(self) -> float:
        if self._lr_scheduler is not None:
            return float(self._lr_scheduler())
        return float(self._lr)

    def set_lr(self, value: float):
        if self._lr_scheduler is not None:
            raise RuntimeError(
                "cannot set_lr when using an LRScheduler; call "
                "scheduler.step() instead")
        self._lr = float(value)

    # ---- update rule (override) ------------------------------------------
    def _init_accumulators(self, param: jax.Array) -> Dict[str, jax.Array]:
        return {name: jnp.zeros_like(param) for name in self._accum_names}

    def _update(self, p: jax.Array, g: jax.Array, state: Dict[str, jax.Array],
                lr, step) -> tuple:
        raise NotImplementedError

    def _update_sparse(self, p, g, state, lr, step) -> tuple:
        """Row-sparse update (g: merged SelectedRows). Reference: the
        sparse optimizer functors (sgd_op.h, adam_op.h SparseAdamFunctor)
        — only SGD/Adam implement them; everything else fails loudly."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support sparse "
            f"(SelectedRows) gradients; use SGD or Adam, or construct "
            f"the Embedding with sparse=False")

    # ---- decoupled weight decay hook (AdamW/Lamb override) ---------------
    _decoupled_wd = 0.0

    def _apply_decay(self, p, g, param_obj=None):
        """Coupled (L1/L2-into-grad) regularization, reference
        regularizer.py appended decay ops. Per-param regularizer overrides
        the optimizer-level one."""
        reg = getattr(param_obj, "regularizer", None) or self._weight_decay
        if reg is None or self._decoupled_wd:
            return g
        return reg.apply(p, g)

    # ---- eager path -------------------------------------------------------
    def step(self):
        if self._parameters is None:
            raise InvalidArgumentError(
                "Optimizer constructed without parameters; pass "
                "parameters=model.parameters() for dygraph use.")
        from ..core.selected_rows import SelectedRows
        lr = self.get_lr()
        params_grads = [(p, p.grad) for p in self._parameters
                        if p.grad is not None and p.trainable]
        sparse_pg = [(p, g) for p, g in params_grads
                     if isinstance(g, SelectedRows)]
        if sparse_pg:
            if self._grad_clip is not None:
                raise NotImplementedError(
                    "grad_clip with sparse (SelectedRows) gradients is "
                    "not supported; clip needs the dense grad")
            params_grads = [(p, g) for p, g in params_grads
                            if not isinstance(g, SelectedRows)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads + sparse_pg:
            key = p.name
            if key not in self._accumulators:
                self._accumulators[key] = self._init_accumulators(p.data)
            plr = lr * p.optimize_attr.get("learning_rate", 1.0) \
                if hasattr(p, "optimize_attr") else lr
            self._cur_param_name = key
            self._cur_param = p
            if isinstance(g, SelectedRows):
                if (getattr(p, "regularizer", None) or
                        self._weight_decay) is not None and \
                        not self._decoupled_wd:
                    raise NotImplementedError(
                        "coupled weight decay with sparse gradients is "
                        "not supported (the decay term is dense)")
                new_p, new_state = self._update_sparse(
                    p.data, g.merge(), self._accumulators[key], plr,
                    self._step_count + 1)
            else:
                garr = g.data if isinstance(g, Tensor) else g
                garr = self._apply_decay(p.data, garr, p)
                new_p, new_state = self._update(
                    p.data, garr, self._accumulators[key], plr,
                    self._step_count + 1)
            p._data = new_p.astype(p.data.dtype)
            self._accumulators[key] = new_state
        self._step_count += 1

    def _grad_stamp(self) -> int:
        """Newest backward-epoch stamp among THIS optimizer's grads (-1 if
        no grads). Grads written by the engine carry `_bw_epoch`
        (core/tensor.py `_accumulate_grad`); manually-assigned grads count
        as epoch 0 so a first minimize() consumes them."""
        newest = -1
        for p in self._parameters or []:
            if p.trainable and p.grad is not None:
                newest = max(newest, getattr(p.grad, "_bw_epoch", 0))
        return newest

    def _ensure_fresh_grads(self, loss):
        """Run loss.backward() only if no backward wrote this optimizer's
        grads since its last minimize; record the consumed stamp. Shared by
        Optimizer.minimize and AmpScaler.minimize."""
        stamp = self._grad_stamp()
        if stamp <= getattr(self, "_seen_grad_stamp", -1):
            loss.backward()
            stamp = self._grad_stamp()
        self._seen_grad_stamp = stamp

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        """Reference dygraph semantics (optimizer.py minimize): grads are
        collected, not recomputed — the canonical `loss.backward();
        opt.minimize(loss)` must not run backward twice. A fresh backward
        runs here only when none happened for THIS optimizer's parameters
        since its last minimize (a global backward counter would let a
        second model's backward mask this one's stale grads).

        Static mode: a symbolic loss records the train hook on the
        default Program (reference static minimize appended backward +
        optimizer ops); Executor.run then executes the fused step."""
        from ..static.program import Variable, default_main_program, \
            install_minimize
        if isinstance(loss, Variable):
            # the loss's OWNING program, not the current default — the
            # guard that recorded it may have exited already
            install_minimize(loss.program or default_main_program(),
                             loss, self)
            return None, []
        self._ensure_fresh_grads(loss)
        self.step()
        return None, [(p, p.grad) for p in (self._parameters or [])]

    def clear_grad(self, set_to_zero=False):
        for p in self._parameters or []:
            p.clear_grad()

    clear_gradients = clear_grad

    # ---- functional path (compiled trainers) ------------------------------
    def init_state(self, params):
        """params: pytree of jax.Array -> state pytree (same structure of
        dicts). Used by jit/pjit train steps; state inherits param sharding."""
        return jax.tree_util.tree_map(self._init_accumulators, params)

    def apply_gradients(self, params, grads, state, lr=None, step=None):
        """Pure update over pytrees. Returns (new_params, new_state)."""
        lr = self.get_lr() if lr is None else lr
        step = (self._step_count + 1) if step is None else step
        if self._grad_clip is not None:
            grads = self._grad_clip.clip_arrays(grads)
        if self._weight_decay is not None and not self._decoupled_wd:
            grads = jax.tree_util.tree_map(
                lambda p, g: self._weight_decay.apply(p, g), params, grads)
        paths_p, treedef = jax.tree_util.tree_flatten_with_path(params)
        leaves_g = treedef.flatten_up_to(grads)
        leaves_s = treedef.flatten_up_to(state)
        new_p, new_s = [], []
        for (path, p), g, s in zip(paths_p, leaves_g, leaves_s):
            structured = _path_to_name(path)
            if self._param_name_map is not None:
                self._cur_param_name = self._param_name_map.get(
                    structured, structured)
            else:
                self._cur_param_name = structured
            self._cur_param = (self._param_obj_map or {}).get(structured)
            plr = lr
            if self._cur_param is not None and hasattr(
                    self._cur_param, "optimize_attr"):
                plr = lr * self._cur_param.optimize_attr.get(
                    "learning_rate", 1.0)
            np_, ns_ = self._update(p, g, s, plr, step)
            new_p.append(np_.astype(p.dtype))
            new_s.append(ns_)
        return (jax.tree_util.tree_unflatten(treedef, new_p),
                jax.tree_util.tree_unflatten(treedef, new_s))

    # ---- state dict -------------------------------------------------------
    def state_dict(self):
        sd = {}
        for pname, accs in self._accumulators.items():
            for aname, arr in accs.items():
                sd[f"{pname}@{aname}"] = Tensor(arr)
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"] = self._lr_scheduler.state_dict()
        sd["@step"] = self._step_count
        return sd

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step", 0))
        if self._lr_scheduler is not None and "LR_Scheduler" in state_dict:
            self._lr_scheduler.set_state_dict(state_dict["LR_Scheduler"])
        for key, val in state_dict.items():
            if key in ("LR_Scheduler", "@step") or "@" not in key:
                continue
            pname, aname = key.rsplit("@", 1)
            arr = val.data if isinstance(val, Tensor) else jnp.asarray(val)
            self._accumulators.setdefault(pname, {})[aname] = arr

    @property
    def _learning_rate(self):
        return self._lr
