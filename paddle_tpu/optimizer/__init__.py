"""paddle.optimizer parity (reference python/paddle/optimizer/)."""
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Momentum, Adagrad, RMSProp, Adadelta, Adam, AdamW, Adamax, Lamb,
    Lars)
from . import lr  # noqa: F401
from .ema import ExponentialMovingAverage  # noqa: F401
