"""paddle_tpu — a TPU-native deep-learning framework.

A ground-up re-design of PaddlePaddle's capabilities (reference:
/root/reference, efreading/Paddle ~v2.0) for TPU: JAX/XLA is the compiled
execution engine (replacing the reference's C++ Executor + CUDA kernel
registry), Pallas provides custom TPU kernels, and jax.sharding meshes
replace NCCL ring-id collectives. The public API mirrors paddle 2.x so a
reference user can switch with minimal changes.

Layer map vs the reference (SURVEY.md §1):
- layers 0-3 (platform/memory/framework/operators) -> core/ + tensor/ over
  XLA; HBM is runtime-managed, kernels are jnp/lax/Pallas lowerings.
- layer 4 (imperative) -> core/autograd eager tape.
- layers 5/9 (distributed) -> distributed/ (mesh + collectives + fleet).
- layers 7-8 (python api) -> this package's nn/optimizer/amp/io/jit/...
- layer 10 (hapi) -> hapi/Model. layer 11 (inference) -> jit.save + export.
"""
from __future__ import annotations

__version__ = "0.1.0"
full_version = __version__
# reference paddle.version exports a build commit id; stamped at package
# build in the reference, a constant here
commit = "unknown"

import warnings as _warnings

# int64/float64 silently canonicalize to 32-bit unless JAX x64 is enabled;
# that is the intended TPU behavior (int32/bf16-native), so hide the noise.
_warnings.filterwarnings(
    "ignore", message=".*requested in astype is not available.*")
_warnings.filterwarnings(
    "ignore", message=".*Explicitly requested dtype.*is not available.*")

from .core.tensor import Parameter, Tensor, to_tensor, is_tensor  # noqa: F401
from .core.autograd import (no_grad, enable_grad, set_grad_enabled,  # noqa: F401
                            is_grad_enabled, grad)
from .core.random import seed, get_rng_state, set_rng_state  # noqa: F401
from .core.dtype import (  # noqa: F401
    set_default_dtype, get_default_dtype,
    bool_, uint8, int8, int16, int32, int64,
    float16, bfloat16, float32, float64, complex64, complex128,
)
from .core.flags import set_flags, get_flags  # noqa: F401

from .tensor import *  # noqa: F401,F403
from .tensor import tensor_methods as _tensor_methods  # noqa: F401  (patch Tensor)

from . import tensor  # noqa: F401
# `from .tensor import *` leaks tensor's submodule objects (math, linalg,
# ...) into this namespace because tensor/__init__ has no __all__; the
# public paddle.linalg namespace must be the dedicated module. NB a plain
# `from . import linalg` would return the leaked attribute, not import.
import importlib as _importlib
linalg = _importlib.import_module(".linalg", __name__)
from . import device  # noqa: F401
from .device import (CPUPlace, CUDAPlace, TPUPlace, CUDAPinnedPlace,  # noqa: F401
                     XPUPlace, get_device, set_device,
                     is_compiled_with_cuda, is_compiled_with_xpu)

# the reference's dygraph VarBase role is played by Tensor directly
VarBase = Tensor


def get_cudnn_version():
    """Reference paddle.get_cudnn_version — no cuDNN on TPU."""
    return None


def get_cuda_rng_state():
    """Reference CUDA rng-state accessors map onto the single JAX key
    state (there is no separate device generator)."""
    return get_rng_state()


def set_cuda_rng_state(state):
    return set_rng_state(state)

# Subpackages imported lazily to keep import light and avoid cycles.
_LAZY_MODULES = (
    "nn", "optimizer", "io", "metric", "amp", "jit", "static",
    "distributed", "vision", "text", "hapi", "callbacks", "profiler",
    "framework", "regularizer", "linalg", "distribution", "incubate",
    "utils", "models", "autograd", "extension", "onnx", "observability",
    "autotune",
)


def __getattr__(name):
    if name in _LAZY_MODULES:
        try:
            mod = _importlib.import_module(f".{name}", __name__)
        except ModuleNotFoundError as e:
            # hasattr()/getattr() probing must see AttributeError for a
            # MISSING submodule — but a transitive dep failure (e.g. a
            # broken jax install) must surface as the real import error
            if e.name != f"{__name__}.{name}":
                raise
            raise AttributeError(
                f"module 'paddle_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    if name == "save":
        from .framework.io import save as _save
        return _save
    if name == "load":
        from .framework.io import load as _load
        return _load
    if name == "in_static_mode":
        from .static import in_static_mode
        return in_static_mode
    if name == "summary":
        from .hapi.model_summary import summary as _summary
        return _summary
    if name == "Model":
        from .hapi.model import Model as _Model
        return _Model
    if name == "DataParallel":
        from .distributed.parallel import DataParallel as _DP
        return _DP
    if name == "flops":
        from .hapi.model_summary import flops as _flops
        return _flops
    if name == "ParamAttr":
        from .nn.layer_base import ParamAttr as _PA
        return _PA
    if name == "create_parameter":
        from .static import create_parameter as _cp
        return _cp
    if name == "py_func":
        from .extension import py_func as _pf
        return _pf
    raise AttributeError(f"module 'paddle_tpu' has no attribute {name!r}")


def monkey_patch_math_varbase():
    """reference fluid/dygraph/math_op_patch.py entry point: binds the
    op library onto Tensor. Runs at import here; calling it re-binds
    (idempotent) so late-registered ops become methods too."""
    _tensor_methods._bind()


def monkey_patch_variable():
    """reference fluid/layers/math_op_patch.py: operator overloads on
    static Variables — built into static/program.py Variable here."""
    return None


def in_dygraph_mode():
    """Reference paddle.in_dygraph_mode (alias of in_dynamic_mode)."""
    return in_dynamic_mode()


def enable_dygraph(place=None):
    """Reference paddle.enable_dygraph == leaving static mode."""
    return disable_static(place)


def disable_dygraph():
    """Reference paddle.disable_dygraph == entering static mode."""
    return enable_static()


def in_dynamic_mode():
    """True when executing eagerly (reference paddle.in_dynamic_mode):
    False inside jit tracing AND while static-graph mode is enabled."""
    from .static import in_static_mode
    if in_static_mode():
        return False
    try:
        from .jit.api import in_tracing
        return not in_tracing()
    except ImportError:
        return True


def disable_static(place=None):
    """Leave static-graph mode (reference paddle.disable_static)."""
    from .static import disable_static as _ds
    return _ds()


def enable_static():
    """Enter static-graph mode: paddle.static.data declares symbolic
    inputs, ops record onto the default Program, and
    paddle.static.Executor runs the captured graph (see
    static/program.py)."""
    from .static import enable_static as _es
    return _es()
