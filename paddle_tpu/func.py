"""Functional bridge: Layer -> pure function over a param pytree.

This is the TPU-native replacement for the reference's dygraph-to-static
ProgramTranslator (fluid/dygraph/dygraph_to_static/program_translator.py:756)
— instead of AST-rewriting Python into a ProgramDesc, we TRACE the layer's
forward with its parameters swapped for function arguments, which jax.jit /
jax.grad / shard_map then compile. 15 AST transformer passes collapse into
~60 lines because XLA traces Python directly.
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, Tuple

import jax

from .core.tensor import Tensor
from .nn.layer_base import Layer

__all__ = ["functional_state", "functional_call", "functional_forward",
           "functional_apply"]


def functional_state(layer: Layer) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """Split a layer into (params, buffers) dicts of raw jax.Arrays keyed
    by structured name."""
    params = {name: p.data for name, p in layer.named_parameters()}
    buffers = {name: b.data for name, b in layer.named_buffers()
               if b is not None}
    return params, buffers


@contextlib.contextmanager
def _swapped(layer: Layer, params: Dict[str, Any], buffers: Dict[str, Any]):
    """Temporarily bind arrays (possibly tracers) into the layer's
    parameter/buffer tensors; restore originals on exit."""
    originals = {}
    tensors = dict(layer.named_parameters())
    buf_tensors = dict(layer.named_buffers())
    for name, arr in params.items():
        t = tensors[name]
        originals[id(t)] = (t, t._data)
        t._data = arr
    for name, arr in (buffers or {}).items():
        t = buf_tensors.get(name)
        if t is None:
            continue
        originals[id(t)] = (t, t._data)
        t._data = arr
    try:
        yield buf_tensors
    finally:
        for t, data in originals.values():
            t._data = data


def functional_call(layer: Layer, params: Dict[str, Any],
                    buffers: Dict[str, Any], *args, training=None, **kwargs):
    """Run layer.forward with `params`/`buffers` bound, returning
    (outputs, new_buffers). Outputs keep their Tensor wrappers unwrapped
    to raw arrays so the result is a clean pytree for jit.

    new_buffers captures in-place buffer mutations (BatchNorm running
    stats) — the functional analogue of the reference's mean_out/var_out
    aliased outputs (batch_norm_op.cc).
    """
    prev_mode = layer.training
    if training is not None:
        layer.train() if training else layer.eval()
    try:
        with _swapped(layer, params, buffers) as buf_tensors:
            wrapped_args = [Tensor(a) if not isinstance(a, Tensor) else a
                            for a in args]
            out = layer(*wrapped_args, **kwargs)
            new_buffers = {name: t.data for name, t in buf_tensors.items()
                           if t is not None and name in (buffers or {})}
            # unwrap INSIDE the swap: a forward may return a parameter
            # object itself (e.g. the tied LM-head weight for the fused
            # loss); reading .data after restore would silently swap the
            # traced value for the stale concrete array and drop its
            # gradient
            out = _unwrap(out)
        return out, new_buffers
    finally:
        if training is not None:
            layer.train() if prev_mode else layer.eval()


def functional_forward(layer: Layer, params, *args, **kwargs):
    """Convenience: functional_call without buffer plumbing."""
    out, _ = functional_call(layer, params, {}, *args, **kwargs)
    return out


def functional_apply(layer: Layer, method: str, params: Dict[str, Any],
                     *args, **kwargs):
    """Run a named METHOD of `layer` with `params` bound, returning the
    method's outputs with Tensors unwrapped to raw arrays.

    Unlike :func:`functional_call` this does not Tensor-wrap positional
    args — non-array pytrees (a serving engine's StaticKVCache, scalar
    ints) pass through untouched — and it targets methods beyond
    ``forward`` (``prefill`` / ``decode_step`` on GPTForCausalLM), which
    is what the inference engine jits.
    """
    with _swapped(layer, params, {}):
        out = getattr(layer, method)(*args, **kwargs)
        out = _unwrap(out)
    return out


def _unwrap(out):
    return jax.tree_util.tree_map(
        lambda x: x.data if isinstance(x, Tensor) else x, out,
        is_leaf=lambda x: isinstance(x, Tensor))
