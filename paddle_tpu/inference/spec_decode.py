"""Speculative decoding: a small draft GPT proposes, the target verifies.

Leviathan et al., *Fast Inference from Transformers via Speculative
Decoding*: decode is bandwidth-bound — every single-token step streams
the whole model + KV cache through the chip to emit ONE token.  A small
draft model can propose K tokens cheaply; the target model then scores
all K+1 positions in ONE windowed forward (the multi-token variant of
``ops.decode_attention`` — same bytes streamed as a single decode step)
and keeps the longest prefix of accepted proposals plus one bonus token
from its own distribution.  Temperature-0 slots use the greedy rule
(match the target's argmax), so the emitted stream is TOKEN-IDENTICAL
to the target-only rollout — speculation changes the schedule, never
the text.  Temperature>0 slots run the FULL rejection-sampling rule
(Leviathan Alg. 1): proposal ``d_i ~ q_i`` accepts with probability
``min(1, p_i(d_i)/q_i(d_i))`` over the WARPED (temperature/top-k/top-p)
distributions, and the first rejected position resamples from the
residual ``norm(max(p - q, 0))`` — in-graph, fixed shapes, so the
committed stream is a faithful sample from the target distribution and
a seeded engine replays the same stream.  With an agreeable draft, each
tick emits ~K+1 tokens for one target pass + one host sync, and the
decode loop's HBM bytes per emitted token drop proportionally.

Mechanics per tick (ONE fixed-shape jitted call — the zero-recompile
contract of the engine survives):

1. **Draft catch-up**: the tokens the scheduler committed last tick that
   the draft has not processed (1..2 of them — the bonus token, plus the
   last proposal when everything was accepted) ride in as a fixed
   ``[B, K+1]`` window; a windowed draft forward folds them into the
   draft's own StaticKVCache and its last valid logit row proposes
   draft token 1.
2. **Propose**: K-1 single-token draft decode steps propose the rest.
3. **Verify**: the target runs ONE windowed forward over
   ``[last_committed, d_1..d_K]`` — writing all K+1 k/v into its cache
   in-graph (dense scatter or paged block-table scatter) — and takes
   greedy ``g_0..g_K``.
4. **Accept**: ``n_acc = longest prefix with d_i == g_{i-1}``; commit
   ``g_0..g_{n_acc}`` (the standard rule: every accepted draft plus one
   bonus token).  Cache lengths advance by the committed count
   in-graph; rejected positions hold garbage ABOVE the advanced length
   — the masked-garbage convention every decode path here already uses
   — and are overwritten by the next tick's window.

The draft always rides a dense StaticKVCache (it is small; block
accounting for it would buy nothing); the TARGET cache is whatever the
engine runs — dense or paged, fp or int8 — which is the matrix the
tests pin down.  ``PADDLE_TPU_SPEC_K`` arms it engine-wide, for greedy
AND sampled traffic (ISSUE 18: temperature>0 requests no longer bypass
the spec path).  Under a tp serving mesh the draft's params and cache
shard exactly like the target's (engine._shard_over_mesh helpers), so
the tick executable compiles SPMD end to end.

Capacity caveat: a tick writes its whole K+1 window before knowing how
much commits, so a stream retires once ``len + K + 1`` would pass
``max_seq_len`` — up to K tokens earlier than a non-speculative
engine.  Token identity therefore holds whenever
``prompt + max_new + K <= max_seq`` (the sane deployment shape);
streams cut by the window margin are counted in
``stats['spec_capacity_retirements']``.
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..distributed import moe as _moe
from ..func import functional_apply, functional_state
from ..models.gpt import StaticKVCache

__all__ = ["SpecDecoder", "resolve_spec_k"]


def resolve_spec_k(spec_k: Optional[int]) -> int:
    """Draft window size: explicit arg, else PADDLE_TPU_SPEC_K, else 0
    (speculation off)."""
    if spec_k is not None:
        return int(spec_k)
    return int(os.environ.get("PADDLE_TPU_SPEC_K", 0) or 0)


class SpecDecoder:
    """The engine's speculative-decoding half: owns the draft model's
    params + dense KV cache and the compiled tick executables.

    The ENGINE stays the scheduler — admission, EOS/deadline retirement,
    preemption and block accounting are untouched; this class only
    replaces the one-token decode step with the K+1-token tick and
    keeps the per-slot catch-up window (`win`/`nprev`) that makes the
    draft cache converge to the committed stream.
    """

    def __init__(self, engine, draft_model, k: int):
        if k < 1:
            raise ValueError(f"spec_k must be >= 1, got {k}")
        draft_model.eval()
        dcfg = draft_model.cfg
        tcfg = engine.model.cfg
        if dcfg.vocab_size != tcfg.vocab_size:
            raise ValueError(
                f"draft vocab {dcfg.vocab_size} != target vocab "
                f"{tcfg.vocab_size}")
        if dcfg.max_seq_len < engine.max_seq_len:
            raise ValueError(
                f"draft max_seq_len {dcfg.max_seq_len} < engine "
                f"max_seq_len {engine.max_seq_len} — the draft must "
                f"reach every position the target serves")
        self.engine = engine
        self.k = int(k)
        self.draft = draft_model
        self.draft_params, _ = functional_state(draft_model)
        # the draft rides a DENSE static cache regardless of the
        # target's layout: per-slot lengths live in-graph (advanced by
        # the tick itself, including the rollback of rejected
        # proposals), so the host never tracks draft state
        self.draft_cache = draft_model.init_kv_cache(
            engine.batch_slots, engine.max_seq_len)
        # pod-scale serving (ISSUE 18): the draft rides the SAME mesh —
        # params by the parallel-layer pspecs, dense cache slots/heads
        # over dp/tp — so the whole tick compiles SPMD
        if engine.mesh is not None:
            try:
                self.draft_params = engine._shard_params_over(
                    engine.mesh, self.draft_params, draft_model)
                self.draft_cache = engine._shard_dense_cache_arrays(
                    engine.mesh, self.draft_cache)
            except Exception as e:
                engine._shard_failed("spec_draft", e)
        # per-slot catch-up window: committed tokens the draft has not
        # seen yet (1 after a fresh admission — the first sampled
        # token; up to 2 mid-stream)
        kp1 = self.k + 1
        self.win = np.zeros((engine.batch_slots, kp1), np.int32)
        self.nprev = np.ones(engine.batch_slots, np.int32)
        dargs = (2, 3) if engine._donate else ()
        self._tick_dense_jit = jax.jit(self._tick_dense_fn,
                                       donate_argnums=dargs)
        self._tick_paged_jit = jax.jit(self._tick_paged_fn,
                                       donate_argnums=dargs)
        self._draft_prefill_jit = jax.jit(
            self._draft_prefill_fn,
            donate_argnums=(1,) if engine._donate else ())

    # ---- compiled functions -------------------------------------------
    def _draft_prefill_fn(self, params, cache, ids, slot, prompt_len):
        return functional_apply(self.draft, "prefill", params, ids,
                                cache, slot, prompt_len)

    def _warped_probs(self, logits, temps, top_ps):
        """The engine sampler's warping (temperature + static top-k +
        per-slot top-p) as a PROBABILITY vector — the p and q the
        rejection rule compares must be the distributions actually
        sampled from, not the raw softmaxes.  logits [N, V] f32;
        returns [N, V] probs (rows with temp<=0 are still valid — they
        are simply never read, greedy rows use argmax)."""
        eng = self.engine
        logits = logits.astype(jnp.float32)
        v = logits.shape[-1]
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        if eng.top_k and eng.top_k < v:
            kth = jax.lax.top_k(scaled, eng.top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        s_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(s_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        s_logits = jnp.where(csum - probs < top_ps[:, None],
                             s_logits, -1e30)
        s_probs = jax.nn.softmax(s_logits, axis=-1)
        inv = jnp.argsort(sort_idx, axis=-1)   # unsort to token order
        return jnp.take_along_axis(s_probs, inv, axis=-1)

    def _propose_from(self, logits, key, temps, top_ps):
        """One proposal from the draft's logit row: greedy slots take
        argmax, sampled slots draw from the warped distribution q.
        Returns (token [B], q [B, V])."""
        q = self._warped_probs(logits, temps, top_ps)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        sampled = jax.random.categorical(
            key, jnp.log(q + 1e-38), axis=-1).astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy), q

    def _draft_propose(self, d_params, d_cache, last_win, nprev, active,
                       key, temps, top_ps):
        """Catch-up window + K-1 single-token steps -> K draft
        proposals (greedy slots: argmax; sampled slots: drawn from the
        warped draft distribution).  Returns (drafts [B, K],
        q [B, K, V] — the proposal distributions the accept rule
        needs — d_cache, key) with the draft cache advanced past
        everything it processed (catch-up tokens AND proposals — the
        tick rolls rejected proposals back)."""
        logits_d, d_cache = functional_apply(
            self.draft, "verify_step", d_params, last_win, d_cache)
        # advance the draft past the nprev real catch-up tokens
        d_cache = StaticKVCache(
            d_cache.k, d_cache.v,
            d_cache.lengths + nprev.astype(jnp.int32) * active,
            d_cache.k_scale, d_cache.v_scale)
        idx = jnp.maximum(nprev.astype(jnp.int32) - 1, 0)
        last_logits = jnp.take_along_axis(
            logits_d, idx[:, None, None], axis=1)[:, 0]    # [B, V]
        key, sub = jax.random.split(key)
        d_prev, q0 = self._propose_from(last_logits, sub, temps, top_ps)
        drafts, qs = [d_prev], [q0]
        for _ in range(self.k - 1):
            lg, d_cache = functional_apply(
                self.draft, "decode_step", d_params, d_prev, d_cache,
                active)
            key, sub = jax.random.split(key)
            d_prev, qi = self._propose_from(lg, sub, temps, top_ps)
            drafts.append(d_prev)
            qs.append(qi)
        return (jnp.stack(drafts, axis=1), jnp.stack(qs, axis=1),
                d_cache, key)                   # [B, K], [B, K, V]

    def _accept(self, drafts, q, logits_t, active, key, temps, top_ps):
        """The rejection rule, both temperatures in one fixed-shape
        graph.  logits_t [B, K+1, V] — target logits over
        [last_committed, d_1..d_K]; q [B, K, V] — the warped draft
        distributions the proposals were drawn from.

        Greedy rows (temp<=0): accept while ``d_i == argmax p_i`` —
        the temperature-0 limit of the rule below, kept as the exact
        argmax comparison so greedy streams stay bit-identical to the
        non-speculative engine.

        Sampled rows: position i accepts iff ``u_i * q_i(d_i) <
        p_i(d_i)`` (u ~ U[0,1); the standard min(1, p/q) acceptance),
        and the commit stream is the accepted prefix plus one token
        from the residual ``norm(max(p - q, 0))`` at the first
        rejected position — with ``q_K ≡ 0`` so a fully-accepted
        window's bonus is a plain sample from ``p_K``.  The residual
        is computed at EVERY position (fixed shapes) and gathered at
        ``n_acc``; a numerically zero residual (p == q) falls back to
        sampling p itself, which is the correct limit.

        Returns (toks [B, K+1] — the committed stream per row,
        n_acc [B], n_emit [B] = (n_acc+1)·active, key)."""
        b, kp1, v = logits_t.shape
        g = jnp.argmax(logits_t, axis=-1).astype(jnp.int32)
        match = (drafts == g[:, :self.k]).astype(jnp.int32)
        n_acc_g = jnp.sum(jnp.cumprod(match, axis=1), axis=1)
        # warped target probs p over all K+1 positions (row-broadcast
        # of the per-slot knobs)
        t_rep = jnp.repeat(temps, kp1)
        tp_rep = jnp.repeat(top_ps, kp1)
        p = self._warped_probs(logits_t.reshape(b * kp1, v),
                               t_rep, tp_rep).reshape(b, kp1, v)
        key, k_u, k_r = jax.random.split(key, 3)
        u = jax.random.uniform(k_u, (b, self.k))
        p_d = jnp.take_along_axis(
            p[:, :self.k], drafts[:, :, None], axis=2)[:, :, 0]
        q_d = jnp.take_along_axis(q, drafts[:, :, None], axis=2)[:, :, 0]
        acc_s = (u * q_d < p_d).astype(jnp.int32)
        n_acc_s = jnp.sum(jnp.cumprod(acc_s, axis=1), axis=1)
        q_pad = jnp.concatenate([q, jnp.zeros((b, 1, v), q.dtype)],
                                axis=1)
        res = jnp.maximum(p - q_pad, 0.0)
        rsum = jnp.sum(res, axis=-1, keepdims=True)
        res = jnp.where(rsum > 0, res / jnp.maximum(rsum, 1e-38), p)
        r_tok = jax.random.categorical(
            k_r, jnp.log(res.reshape(b * kp1, v) + 1e-38),
            axis=-1).reshape(b, kp1).astype(jnp.int32)
        # sampled-row commit stream: accepted drafts, then the residual
        # draw at n_acc (positions past it are never read by the host)
        pos = jnp.arange(kp1)[None, :]
        bonus = jnp.take_along_axis(r_tok, n_acc_s[:, None], axis=1)
        d_pad = jnp.concatenate(
            [drafts, jnp.zeros((b, 1), jnp.int32)], axis=1)
        toks_s = jnp.where(pos < n_acc_s[:, None], d_pad, bonus)
        sampled_row = (temps > 0)
        toks = jnp.where(sampled_row[:, None], toks_s, g)
        n_acc = jnp.where(sampled_row, n_acc_s, n_acc_g)
        n_emit = (n_acc + 1) * active.astype(jnp.int32)
        return toks, n_acc, n_emit, key

    def _draft_rollback(self, d_cache, n_acc, active):
        """Proposals past the accepted prefix are NOT part of the
        committed stream: roll the draft's in-graph lengths back over
        them (their k/v become masked garbage, overwritten by the next
        catch-up window).  Proposal d_K was never fed back, so the
        overshoot is K-1 - n_acc, floored at 0."""
        overshoot = jnp.maximum(self.k - 1 - n_acc, 0) * \
            active.astype(jnp.int32)
        return StaticKVCache(d_cache.k, d_cache.v,
                             d_cache.lengths - overshoot,
                             d_cache.k_scale, d_cache.v_scale)

    def _tick_dense_fn(self, t_params, d_params, t_cache, d_cache,
                       last_win, nprev, active, key, temps, top_ps):
        """One dense-target spec tick; returns (out [B, K+2] int32 —
        the K+1 committed-stream tokens + the committed count, ONE host
        readback — key, t_cache, d_cache)."""
        drafts, q, d_cache, key = self._draft_propose(
            d_params, d_cache, last_win, nprev, active, key, temps,
            top_ps)
        idx = jnp.maximum(nprev.astype(jnp.int32) - 1, 0)
        t0 = jnp.take_along_axis(last_win, idx[:, None], axis=1)
        window = jnp.concatenate([t0, drafts], axis=1)     # [B, K+1]
        # expert-stats scope (ISSUE 19): the collector brackets only
        # the TARGET verify — a MoE draft (possibly with a different
        # expert count) must not fold into the target's load histogram
        with _moe.collect_expert_stats() as b:
            logits_t, t_cache = functional_apply(
                self.engine.model, "verify_step", t_params, window,
                t_cache)
        moe = _moe.fold_expert_stats(b)
        toks, n_acc, n_emit, key = self._accept(
            drafts, q, logits_t, active, key, temps, top_ps)
        t_cache = StaticKVCache(
            t_cache.k, t_cache.v,
            jnp.minimum(t_cache.lengths + n_emit, t_cache.capacity),
            t_cache.k_scale, t_cache.v_scale)
        d_cache = self._draft_rollback(d_cache, n_acc, active)
        out = jnp.concatenate([toks, n_emit[:, None]], axis=1)
        return out, key, t_cache, d_cache, moe

    def _tick_paged_fn(self, t_params, d_params, t_cache, d_cache,
                       last_win, nprev, active, tables, t_lens, key,
                       temps, top_ps):
        """Paged-target spec tick: identical flow with the target's
        window scattered through the block tables; target lengths are
        HOST state (the scheduler advances them from the readback)."""
        drafts, q, d_cache, key = self._draft_propose(
            d_params, d_cache, last_win, nprev, active, key, temps,
            top_ps)
        idx = jnp.maximum(nprev.astype(jnp.int32) - 1, 0)
        t0 = jnp.take_along_axis(last_win, idx[:, None], axis=1)
        window = jnp.concatenate([t0, drafts], axis=1)
        with _moe.collect_expert_stats() as b:
            logits_t, t_cache = functional_apply(
                self.engine.model, "verify_step_paged", t_params, window,
                t_cache, tables, t_lens)
        moe = _moe.fold_expert_stats(b)
        toks, n_acc, n_emit, key = self._accept(
            drafts, q, logits_t, active, key, temps, top_ps)
        d_cache = self._draft_rollback(d_cache, n_acc, active)
        out = jnp.concatenate([toks, n_emit[:, None]], axis=1)
        return out, key, t_cache, d_cache, moe

    # ---- host-side hooks the engine calls -----------------------------
    def on_admit(self, req, slot: int, first_tok: int):
        """A request just prefilled into `slot` on the TARGET: prefill
        the draft over the same (full) prompt and seed the catch-up
        window with the first sampled token."""
        eng = self.engine
        prompt = req.effective_prompt()
        bucket = eng._bucket_for(prompt.size)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :prompt.size] = prompt
        _, cache = eng._timed_exec(
            "prefill_ms", ("draft_prefill", bucket),
            self._draft_prefill_jit,
            self.draft_params, self.draft_cache, jnp.asarray(ids),
            np.int32(slot), np.int32(prompt.size))
        self.draft_cache = cache
        self.win[slot, :] = 0
        self.win[slot, 0] = first_tok
        self.nprev[slot] = 1

    def on_release(self, slot: int):
        """Slot retired/preempted: neutralize its spec state (the draft
        cache row resets at the next admission's prefill)."""
        self.win[slot, :] = 0
        self.nprev[slot] = 1

    def after_commit(self, slot: int, emitted: np.ndarray):
        """The scheduler committed `emitted` tokens for `slot` this
        tick: queue the suffix the draft has not processed as the next
        catch-up window.  The draft HAS the accepted proposals it fed
        itself (min(n_acc, K-1) of them); it lacks the bonus token and,
        when everything was accepted, the never-fed d_K."""
        n_emit = len(emitted)
        in_cache = min(n_emit - 1, self.k - 1)
        tail = emitted[in_cache:]
        self.win[slot, :] = 0
        self.win[slot, :len(tail)] = tail
        self.nprev[slot] = len(tail)

    def tick(self, active: np.ndarray, accum_moe: bool = True):
        """Run one spec tick over the current slots; returns the host
        readback ``out [B, K+2]`` (K+1 committed-stream tokens +
        committed count per slot).  The engine's PRNG key threads
        through the tick (sampled acceptance + residual draws) and
        advances exactly once per tick, so a seeded engine replays the
        same stream.  ``accum_moe=False`` (warmup) discards the tick's
        expert-load fold — throwaway tokens stay out of the balance
        stats."""
        eng = self.engine
        if eng.kv_layout == "paged":
            out, key, t_cache, d_cache, moe = eng._timed_exec(
                "decode_ms", ("spec_tick", 0), self._tick_paged_jit,
                eng.params, self.draft_params, eng.cache,
                self.draft_cache, jnp.asarray(self.win),
                jnp.asarray(self.nprev), jnp.asarray(active),
                jnp.asarray(eng._tables),
                jnp.asarray(eng._slot_len.astype(np.int32)),
                eng._key, jnp.asarray(eng._temps),
                jnp.asarray(eng._top_ps))
        else:
            out, key, t_cache, d_cache, moe = eng._timed_exec(
                "decode_ms", ("spec_tick", 0), self._tick_dense_jit,
                eng.params, self.draft_params, eng.cache,
                self.draft_cache, jnp.asarray(self.win),
                jnp.asarray(self.nprev), jnp.asarray(active),
                eng._key, jnp.asarray(eng._temps),
                jnp.asarray(eng._top_ps))
        eng._key = key
        eng.cache = t_cache
        self.draft_cache = d_cache
        if accum_moe:
            eng._accum_moe(moe)
        return out

    def step_hbm_bytes(self) -> int:
        """One draft decode step's HBM read traffic (params amortized
        over the batch + the dense draft KV extent) — the spec-adjusted
        decode_hbm_bytes_per_tok accounting in engine.stats."""
        pbytes = 0
        for leaf in jax.tree_util.tree_leaves(self.draft_params):
            pbytes += int(np.prod(leaf.shape)) * \
                jnp.dtype(leaf.dtype).itemsize
        dcfg = self.draft.cfg
        eng = self.engine
        kv_item = jnp.dtype(self.draft_cache.k.dtype).itemsize
        kv = (2 * dcfg.num_layers * eng.max_seq_len *
              dcfg.num_kv_heads * dcfg.head_dim * kv_item)
        return int(pbytes / eng.batch_slots + kv)

    def warmup(self):
        """Compile the tick executable (and one draft prefill per
        engine bucket) before traffic, then zero both caches' lengths
        — the same throwaway-token discipline as engine.warmup."""
        eng = self.engine
        for b in eng.buckets:
            ids = jnp.zeros((1, b), jnp.int32)
            _, cache = eng._timed_exec(
                "prefill_ms", ("draft_prefill", b),
                self._draft_prefill_jit,
                self.draft_params, self.draft_cache, ids,
                np.int32(0), np.int32(1))
            self.draft_cache = cache
        active = np.zeros(eng.batch_slots, np.int32)
        self.tick(active, accum_moe=False)
        # reset lengths COMMITTED to the serving mesh, exactly like
        # engine._warmup_dense: an uncommitted zeros operand is a
        # different jit cache key than the committed one the warmup
        # trace used, and the first real prefill would recompile
        zeros = jnp.zeros((eng.batch_slots,), jnp.int32)
        if eng.mesh is not None:
            try:
                zeros = eng._put(eng.mesh, zeros, ("dp",))
            except Exception as e:
                eng._shard_failed("spec_warmup_lengths", e)
        self.draft_cache = StaticKVCache(
            self.draft_cache.k, self.draft_cache.v, zeros,
            self.draft_cache.k_scale, self.draft_cache.v_scale)
        if eng.kv_layout != "paged":
            eng.cache = StaticKVCache(
                eng.cache.k, eng.cache.v, zeros,
                eng.cache.k_scale, eng.cache.v_scale)
