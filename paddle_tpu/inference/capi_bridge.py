"""Python side of the C inference API.

Reference: paddle/fluid/inference/capi/pd_predictor.cc — there the C
functions wrap the C++ AnalysisPredictor; here they wrap the XLA
runtime (deserialized StableHLO + params via jit.load), reached through
an embedded CPython.  The C shim (capi/pd_inference.c) calls exactly
three functions: create / run / destroy, trafficking in raw bytes +
shape + dtype-name triples so no numpy C API crosses the boundary.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_predictors: Dict[int, object] = {}
_next_handle = 1


def create(model_path: str) -> int:
    """Load a jit.save export; returns an opaque handle."""
    global _next_handle
    from ..jit.api import load
    layer = load(model_path)
    h = _next_handle
    _next_handle += 1
    _predictors[h] = layer
    return h


def run(handle: int,
        inputs: List[Tuple[bytes, Tuple[int, ...], str]]
        ) -> List[Tuple[bytes, Tuple[int, ...], str]]:
    """inputs/outputs: (raw little-endian bytes, shape, dtype name)."""
    layer = _predictors[handle]
    args = []
    for raw, shape, dtype in inputs:
        args.append(np.frombuffer(raw, dtype=np.dtype(dtype))
                    .reshape(tuple(shape)))
    out = layer(*args)
    import jax
    leaves = jax.tree_util.tree_leaves(out)
    result = []
    for leaf in leaves:
        a = np.asarray(leaf.data if hasattr(leaf, "data") else leaf)
        result.append((a.tobytes(), tuple(a.shape), a.dtype.name))
    return result


def destroy(handle: int) -> None:
    _predictors.pop(handle, None)


# ---------------------------------------------------------------------------
# native training entry (reference fluid/train/demo: a C++ program that
# loads a saved train program and steps it — here the artifact is the
# serialized StableHLO train step from SpmdTrainer.export_train_step)
# ---------------------------------------------------------------------------
_trainers: Dict[int, dict] = {}


def create_trainer(path: str) -> int:
    global _next_handle
    import pickle

    import jax
    import jax.numpy as jnp
    from jax import export as jexport
    with open(path + ".pdtrain", "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path + ".pdtrainstate", "rb") as f:
        state = pickle.load(f)
    h = _next_handle
    _next_handle += 1
    _trainers[h] = {
        "exported": exported,
        "params": jax.tree_util.tree_map(jnp.asarray, state["params"]),
        "opt_state": jax.tree_util.tree_map(jnp.asarray,
                                            state["opt_state"]),
        "buffers": jax.tree_util.tree_map(jnp.asarray, state["buffers"]),
        "lr": float(state["lr"]),
        "step": int(state["step_count"]),
    }
    return h


def trainer_step(handle: int,
                 inputs: List[Tuple[bytes, Tuple[int, ...], str]]
                 ) -> Tuple[bytes, Tuple[int, ...], str]:
    """Run one serialized train step; returns the loss triple."""
    import jax.numpy as jnp
    t = _trainers[handle]
    batch = [jnp.asarray(np.frombuffer(raw, dtype=np.dtype(dt))
                         .reshape(tuple(shape)))
             for raw, shape, dt in inputs]
    res = t["exported"].call(
        t["params"], t["opt_state"], t["buffers"],
        jnp.asarray(t["lr"], jnp.float32),
        jnp.asarray(t["step"] + 1, jnp.int32), *batch)
    t["params"], t["opt_state"], t["buffers"], loss = res
    t["step"] += 1
    a = np.asarray(loss)
    return (a.tobytes(), tuple(a.shape), a.dtype.name)


def destroy_trainer(handle: int) -> None:
    _trainers.pop(handle, None)
