"""Python side of the C inference API.

Reference: paddle/fluid/inference/capi/pd_predictor.cc — there the C
functions wrap the C++ AnalysisPredictor; here they wrap the XLA
runtime (deserialized StableHLO + params via jit.load), reached through
an embedded CPython.  The C shim (capi/pd_inference.c) calls exactly
three functions: create / run / destroy, trafficking in raw bytes +
shape + dtype-name triples so no numpy C API crosses the boundary.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_predictors: Dict[int, object] = {}
_next_handle = 1


def create(model_path: str) -> int:
    """Load a jit.save export; returns an opaque handle."""
    global _next_handle
    from ..jit.api import load
    layer = load(model_path)
    h = _next_handle
    _next_handle += 1
    _predictors[h] = layer
    return h


def run(handle: int,
        inputs: List[Tuple[bytes, Tuple[int, ...], str]]
        ) -> List[Tuple[bytes, Tuple[int, ...], str]]:
    """inputs/outputs: (raw little-endian bytes, shape, dtype name)."""
    layer = _predictors[handle]
    args = []
    for raw, shape, dtype in inputs:
        args.append(np.frombuffer(raw, dtype=np.dtype(dtype))
                    .reshape(tuple(shape)))
    out = layer(*args)
    import jax
    leaves = jax.tree_util.tree_leaves(out)
    result = []
    for leaf in leaves:
        a = np.asarray(leaf.data if hasattr(leaf, "data") else leaf)
        result.append((a.tobytes(), tuple(a.shape), a.dtype.name))
    return result


def destroy(handle: int) -> None:
    _predictors.pop(handle, None)
