"""Prefix-aware routing over N engine replicas.

N replicas behind round-robin are N independent caches: a tenant's
system prompt ends up prefilled N times and each copy is cold N-1
requests out of N.  The fix is the same observation that built the
radix prefix cache (SGLang's cache-aware routing): route a request to
the replica that already HOLDS its prefix.  Each replica's
``RadixPrefixCache`` maintains a block-granular fingerprint set
(``summary()`` — rolling path hashes, updated incrementally on
insert/evict, no tree walk); the router rolls the same fingerprint over
an incoming prompt's chunks and scores every replica by how many
consecutive blocks it could serve (``prefix_cache.score_overlap``).
Highest score wins; scoreless requests — and ties — fall back to
least-loaded, so the router degrades to load balancing exactly when
cache affinity has nothing to say.

This is a HOST-side scheduler over ordinary engines: replicas can be
`InferenceEngine`s in one process (the CPU harness), engines pinned to
different TPU device groups, or (with a thin RPC shim) different hosts
— the router only ever touches prompts, summaries and queue depths,
never device state.  ``policy='round_robin'`` keeps the baseline the
fleet smoke beats.
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _metrics
from .prefix_cache import fingerprint_chain, score_overlap

__all__ = ["Router"]

_POLICIES = ("prefix", "least_loaded", "round_robin")


class Router:
    """Request router over engine replicas.

    Usage::

        router = Router([eng_a, eng_b])          # policy='prefix'
        ridx, rid = router.add_request(prompt, max_new_tokens=64)
        while router.has_work:
            router.step()
        outputs = router.results()               # {(ridx, rid): tokens}
    """

    def __init__(self, replicas: Sequence, policy: str = "prefix",
                 max_load_gap: Optional[int] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, "
                             f"got {policy!r}")
        self.replicas = list(replicas)
        self.policy = policy
        # cache affinity bounded by load: following a prefix hit onto a
        # replica that is already `max_load_gap` requests deeper than
        # the least-loaded one trades a re-prefill for a queue wait —
        # the wrong trade at the tail.  Default: one full slot
        # generation ahead (the SGLang-style balance threshold).
        if max_load_gap is None:
            max_load_gap = max(getattr(r, "batch_slots", 1)
                               for r in self.replicas)
        self.max_load_gap = int(max_load_gap)
        self._rr = itertools.cycle(range(len(self.replicas)))
        # routing stats: the fleet smoke's router-hit-rate column
        self.routed = [0] * len(self.replicas)
        self.requests = 0
        self.prefix_routed = 0        # routed BY a positive overlap
        self.prefix_blocks_routed = 0
        # unified telemetry: routing decisions into the registry, and a
        # lazily-built fleet aggregator (scrape_metrics) that folds each
        # replica's finished-request records into fleet-level metrics
        self._m_routed = _metrics.counter(
            "router_requests_total", "requests placed",
            labels=("policy",)).labels(policy=self.policy)
        self._m_prefix_routed = _metrics.counter(
            "router_prefix_routed_total",
            "requests placed by prefix affinity")
        self._aggregator = None

    # ---- scoring ------------------------------------------------------
    def _load(self, replica) -> int:
        # queued + active + (disaggregated replicas) prefilled-but-not-
        # yet-admitted handoff records — every request the replica has
        # accepted and not finished
        return (len(replica._queue) + replica.num_active
                + len(getattr(replica, "_handoffs", ())))

    def _least_loaded(self) -> int:
        loads = [self._load(r) for r in self.replicas]
        return int(np.argmin(loads))

    def route(self, prompt) -> int:
        """Pick the replica for ``prompt``; returns its index (and
        counts the decision in the router stats)."""
        self.requests += 1
        if self.policy == "round_robin":
            idx = next(self._rr)
        elif self.policy == "least_loaded":
            idx = self._least_loaded()
        else:
            # the fingerprint chain depends only on (prompt, block
            # size): roll it once per distinct block size, then each
            # replica costs a few set lookups
            chains: Dict[int, list] = {}
            scores = []
            for r in self.replicas:
                summ = r.prefix_summary() if hasattr(r, "prefix_summary") \
                    else None
                if not summ:
                    scores.append(0)
                    continue
                bs = int(summ["block_size"])
                if bs not in chains:
                    chains[bs] = fingerprint_chain(prompt, bs)
                scores.append(score_overlap(prompt, summ,
                                            chain=chains[bs]))
            best = max(scores)
            loads = [self._load(r) for r in self.replicas]
            if best > 0:
                # tie on score -> least loaded among the tied
                tied = [i for i, s in enumerate(scores) if s == best]
                idx = min(tied, key=lambda i: loads[i])
                if loads[idx] - min(loads) > self.max_load_gap:
                    # affinity would chase the prefix onto an already-
                    # backed-up replica: balance wins the tail
                    idx = int(np.argmin(loads))
                else:
                    self.prefix_routed += 1
                    self.prefix_blocks_routed += best
                    self._m_prefix_routed.inc()
            else:
                idx = int(np.argmin(loads))
        self.routed[idx] += 1
        self._m_routed.inc()
        return idx

    # ---- request plumbing ---------------------------------------------
    def add_request(self, prompt, **kw) -> Tuple[int, int]:
        """Route + enqueue; returns (replica index, request id)."""
        idx = self.route(prompt)
        return idx, self.replicas[idx].add_request(prompt, **kw)

    @property
    def has_work(self) -> bool:
        return any(r.has_work for r in self.replicas)

    def step(self) -> int:
        """One scheduling round: every replica with work advances one
        step.  Returns tokens produced across the fleet."""
        produced = 0
        for r in self.replicas:
            if r.has_work:
                produced += r.step_or_raise()
        return produced

    def run(self) -> Dict[Tuple[int, int], np.ndarray]:
        while self.has_work:
            self.step()
        return self.results()

    def results(self) -> Dict[Tuple[int, int], np.ndarray]:
        out = {}
        for i, r in enumerate(self.replicas):
            for rid, toks in r.results.items():
                out[(i, rid)] = toks
        return out

    def drain(self, timeout_s: Optional[float] = None) -> List:
        """Drain every replica; returns the concatenated still-queued
        requests (paged pools are leak-checked replica by replica)."""
        leftover = []
        for r in self.replicas:
            leftover.extend(r.drain(timeout_s))
        return leftover

    # ---- telemetry ----------------------------------------------------
    def scrape_metrics(self, monitor=None) -> dict:
        """One fleet aggregation pass: fold every replica's NEW
        finished-request records into the fleet-level registry metrics
        (TTFT histogram, token/request counters, queue-depth and
        block gauges per replica) and optionally feed an SLOMonitor.
        Host-side dict reading only — safe inside a serving loop."""
        if self._aggregator is None:
            from ..observability import FleetAggregator
            self._aggregator = FleetAggregator(self.replicas,
                                               monitor=monitor)
        elif monitor is not None:
            self._aggregator.monitor = monitor
        return self._aggregator.scrape()

    # ---- stats --------------------------------------------------------
    @property
    def stats(self) -> dict:
        """Router-level view: where requests went and why, plus the
        per-replica occupancy/prefix numbers the fleet report quotes."""
        reqs = max(self.requests, 1)
        return {
            "policy": self.policy,
            "replicas": len(self.replicas),
            "requests_routed": self.requests,
            "routed_per_replica": list(self.routed),
            # the router HIT rate: how often cache affinity (not load)
            # made the call
            "router_hit_rate": round(self.prefix_routed / reqs, 4),
            "router_prefix_blocks": self.prefix_blocks_routed,
            "replica_loads": [self._load(r) for r in self.replicas],
        }
