"""Prefix-aware routing over N engine replicas.

N replicas behind round-robin are N independent caches: a tenant's
system prompt ends up prefilled N times and each copy is cold N-1
requests out of N.  The fix is the same observation that built the
radix prefix cache (SGLang's cache-aware routing): route a request to
the replica that already HOLDS its prefix.  Each replica's
``RadixPrefixCache`` maintains a block-granular fingerprint set
(``summary()`` — rolling path hashes, updated incrementally on
insert/evict, no tree walk); the router rolls the same fingerprint over
an incoming prompt's chunks and scores every replica by how many
consecutive blocks it could serve (``prefix_cache.score_overlap``).
Highest score wins; scoreless requests — and ties — fall back to
least-loaded, so the router degrades to load balancing exactly when
cache affinity has nothing to say.

This is a HOST-side scheduler over ordinary engines: replicas can be
`InferenceEngine`s in one process (the CPU harness), engines pinned to
different TPU device groups, or (with a thin RPC shim) different hosts
— the router only ever touches prompts, summaries and queue depths,
never device state.  ``policy='round_robin'`` keeps the baseline the
fleet smoke beats.
"""
from __future__ import annotations

import itertools
import json
import socket
import struct
import threading
from types import SimpleNamespace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability import metrics as _metrics
from .prefix_cache import fingerprint_chain, score_overlap

__all__ = ["Router", "ReplicaRPCServer", "RPCReplicaProxy"]

_POLICIES = ("prefix", "least_loaded", "round_robin")


class Router:
    """Request router over engine replicas.

    Usage::

        router = Router([eng_a, eng_b])          # policy='prefix'
        ridx, rid = router.add_request(prompt, max_new_tokens=64)
        while router.has_work:
            router.step()
        outputs = router.results()               # {(ridx, rid): tokens}
    """

    def __init__(self, replicas: Sequence, policy: str = "prefix",
                 max_load_gap: Optional[int] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if policy not in _POLICIES:
            raise ValueError(f"policy must be one of {_POLICIES}, "
                             f"got {policy!r}")
        self.replicas = list(replicas)
        self.policy = policy
        # cache affinity bounded by load: following a prefix hit onto a
        # replica that is already `max_load_gap` requests deeper than
        # the least-loaded one trades a re-prefill for a queue wait —
        # the wrong trade at the tail.  Default: one full slot
        # generation ahead (the SGLang-style balance threshold).
        if max_load_gap is None:
            max_load_gap = max(getattr(r, "batch_slots", 1)
                               for r in self.replicas)
        self.max_load_gap = int(max_load_gap)
        self._rr = itertools.cycle(range(len(self.replicas)))
        # routing stats: the fleet smoke's router-hit-rate column
        self.routed = [0] * len(self.replicas)
        self.requests = 0
        self.prefix_routed = 0        # routed BY a positive overlap
        self.prefix_blocks_routed = 0
        # unified telemetry: routing decisions into the registry, and a
        # lazily-built fleet aggregator (scrape_metrics) that folds each
        # replica's finished-request records into fleet-level metrics
        self._m_routed = _metrics.counter(
            "router_requests_total", "requests placed",
            labels=("policy",)).labels(policy=self.policy)
        self._m_prefix_routed = _metrics.counter(
            "router_prefix_routed_total",
            "requests placed by prefix affinity")
        self._aggregator = None

    # ---- scoring ------------------------------------------------------
    def _load(self, replica) -> int:
        # queued + active + (disaggregated replicas) prefilled-but-not-
        # yet-admitted handoff records — every request the replica has
        # accepted and not finished
        return (len(replica._queue) + replica.num_active
                + len(getattr(replica, "_handoffs", ())))

    def _least_loaded(self) -> int:
        loads = [self._load(r) for r in self.replicas]
        return int(np.argmin(loads))

    def route(self, prompt) -> int:
        """Pick the replica for ``prompt``; returns its index (and
        counts the decision in the router stats)."""
        self.requests += 1
        if self.policy == "round_robin":
            idx = next(self._rr)
        elif self.policy == "least_loaded":
            idx = self._least_loaded()
        else:
            # the fingerprint chain depends only on (prompt, block
            # size): roll it once per distinct block size, then each
            # replica costs a few set lookups
            chains: Dict[int, list] = {}
            scores = []
            for r in self.replicas:
                summ = r.prefix_summary() if hasattr(r, "prefix_summary") \
                    else None
                if not summ:
                    scores.append(0)
                    continue
                bs = int(summ["block_size"])
                if bs not in chains:
                    chains[bs] = fingerprint_chain(prompt, bs)
                scores.append(score_overlap(prompt, summ,
                                            chain=chains[bs]))
            best = max(scores)
            loads = [self._load(r) for r in self.replicas]
            if best > 0:
                # tie on score -> least loaded among the tied
                tied = [i for i, s in enumerate(scores) if s == best]
                idx = min(tied, key=lambda i: loads[i])
                if loads[idx] - min(loads) > self.max_load_gap:
                    # affinity would chase the prefix onto an already-
                    # backed-up replica: balance wins the tail
                    idx = int(np.argmin(loads))
                else:
                    self.prefix_routed += 1
                    self.prefix_blocks_routed += best
                    self._m_prefix_routed.inc()
            else:
                idx = int(np.argmin(loads))
        self.routed[idx] += 1
        self._m_routed.inc()
        return idx

    # ---- request plumbing ---------------------------------------------
    def add_request(self, prompt, **kw) -> Tuple[int, int]:
        """Route + enqueue; returns (replica index, request id)."""
        idx = self.route(prompt)
        return idx, self.replicas[idx].add_request(prompt, **kw)

    @property
    def has_work(self) -> bool:
        return any(r.has_work for r in self.replicas)

    def step(self) -> int:
        """One scheduling round: every replica with work advances one
        step.  Returns tokens produced across the fleet."""
        produced = 0
        for r in self.replicas:
            if r.has_work:
                produced += r.step_or_raise()
        return produced

    def run(self) -> Dict[Tuple[int, int], np.ndarray]:
        while self.has_work:
            self.step()
        return self.results()

    def results(self) -> Dict[Tuple[int, int], np.ndarray]:
        out = {}
        for i, r in enumerate(self.replicas):
            for rid, toks in r.results.items():
                out[(i, rid)] = toks
        return out

    def drain(self, timeout_s: Optional[float] = None) -> List:
        """Drain every replica; returns the concatenated still-queued
        requests (paged pools are leak-checked replica by replica)."""
        leftover = []
        for r in self.replicas:
            leftover.extend(r.drain(timeout_s))
        return leftover

    # ---- telemetry ----------------------------------------------------
    def scrape_metrics(self, monitor=None) -> dict:
        """One fleet aggregation pass: fold every replica's NEW
        finished-request records into the fleet-level registry metrics
        (TTFT histogram, token/request counters, queue-depth and
        block gauges per replica) and optionally feed an SLOMonitor.
        Host-side dict reading only — safe inside a serving loop."""
        if self._aggregator is None:
            from ..observability import FleetAggregator
            self._aggregator = FleetAggregator(self.replicas,
                                               monitor=monitor)
        elif monitor is not None:
            self._aggregator.monitor = monitor
        return self._aggregator.scrape()

    # ---- stats --------------------------------------------------------
    @property
    def stats(self) -> dict:
        """Router-level view: where requests went and why, plus the
        per-replica occupancy/prefix numbers the fleet report quotes."""
        reqs = max(self.requests, 1)
        return {
            "policy": self.policy,
            "replicas": len(self.replicas),
            "requests_routed": self.requests,
            "routed_per_replica": list(self.routed),
            # the router HIT rate: how often cache affinity (not load)
            # made the call
            "router_hit_rate": round(self.prefix_routed / reqs, 4),
            "router_prefix_blocks": self.prefix_blocks_routed,
            "replica_loads": [self._load(r) for r in self.replicas],
        }


# ---------------------------------------------------------------------------
# Cross-process replica transport (ISSUE 18 satellite, ROADMAP 2c).
#
# The router only ever needs two calls off a replica — ``summary()``
# (the radix-cache fingerprint digest) and ``add_request`` — so the
# transport is deliberately tiny: length-prefixed JSON frames over a
# TCP socket (4-byte big-endian length + UTF-8 JSON body), one
# persistent connection per proxy, request/response lockstep.  The
# server wraps a live engine (or DisaggServingEngine) and serializes
# every engine touch under one lock; the proxy duck-types the replica
# surface the Router, FleetAggregator and fleet load harness read
# (``_queue``/``num_active``/``blocks_in_use``/``_timings``/
# ``request_stats``/``results``) from cached scrape snapshots that
# refresh on every ``step_or_raise``/``refresh_stats`` round trip.
#
# Same-host scope by design: radix fingerprints are Python ``hash()``
# values, so cross-PROCESS summary agreement needs a pinned
# PYTHONHASHSEED — cross-host hardening is the ROADMAP remainder.
# ---------------------------------------------------------------------------

def _json_default(o):
    if isinstance(o, np.integer):
        return int(o)
    if isinstance(o, np.floating):
        return float(o)
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON-serializable: {type(o).__name__}")


def _send_frame(sock, obj) -> None:
    data = json.dumps(obj, default=_json_default).encode("utf-8")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _recv_exact(sock, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _recv_frame(sock) -> Optional[dict]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = struct.unpack(">I", hdr)
    body = _recv_exact(sock, n)
    return None if body is None else json.loads(body.decode("utf-8"))


class ReplicaRPCServer:
    """Expose one replica over the socket protocol.  ``port=0`` binds
    an ephemeral port; ``.address`` is the ``(host, port)`` a proxy
    connects to.  ``lock`` lets a caller share its own exclusion (the
    fleet harness'); default is a private lock — either way every
    engine call runs under it, so concurrent proxy connections are
    safe."""

    def __init__(self, replica, host: str = "127.0.0.1", port: int = 0,
                 lock: Optional[threading.Lock] = None):
        self.replica = replica
        self._lock = lock if lock is not None else threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self.address = self._sock.getsockname()
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None

    def start(self) -> "ReplicaRPCServer":
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    msg = _recv_frame(conn)
                except OSError:
                    break
                if msg is None:
                    break
                try:
                    resp = {"ok": True, "r": self._dispatch(msg)}
                except Exception as e:
                    resp = {"ok": False,
                            "e": f"{type(e).__name__}: {e}"}
                try:
                    _send_frame(conn, resp)
                except OSError:
                    break

    def _snapshot(self) -> dict:
        """The proxy-side replica surface in one JSON-safe dict.
        Caller holds the lock."""
        r = self.replica
        return {
            "timings": dict(r._timings),
            "request_stats": {int(k): dict(v)
                              for k, v in r.request_stats.items()},
            "results": {int(k): np.asarray(v).tolist()
                        for k, v in r.results.items()},
            "queue_len": len(r._queue),
            "handoffs": len(getattr(r, "_handoffs", ())),
            "num_active": int(r.num_active),
            "blocks_in_use": r.blocks_in_use,
            "has_work": bool(r.has_work),
        }

    def _dispatch(self, msg: dict):
        m = msg.get("m")
        r = self.replica
        if m == "ping":
            cfg = getattr(r.model, "cfg", None)
            return {"vocab_size": int(getattr(cfg, "vocab_size",
                                              1 << 15)),
                    "batch_slots": int(getattr(r, "batch_slots", 1)),
                    "request_stats_cap": int(getattr(
                        r, "_request_stats_cap", 4096))}
        if m == "summary":
            with self._lock:
                summ = r.prefix_summary()
            if summ is None:
                return None
            out = dict(summ)
            out["fingerprints"] = list(summ["fingerprints"])
            return out
        if m == "add_request":
            prompt = np.asarray(msg["prompt"], np.int32)
            with self._lock:
                return int(r.add_request(prompt, **msg.get("kw", {})))
        if m == "load":
            with self._lock:
                return {"queue_len": len(r._queue),
                        "handoffs": len(getattr(r, "_handoffs", ())),
                        "num_active": int(r.num_active),
                        "blocks_in_use": r.blocks_in_use,
                        "has_work": bool(r.has_work)}
        if m == "step":
            with self._lock:
                produced = r.step_or_raise()
                return {"produced": int(produced),
                        "snap": self._snapshot()}
        if m == "scrape":
            with self._lock:
                return self._snapshot()
        if m == "stop":
            self.stop()
            return True
        raise ValueError(f"unknown RPC method {m!r}")


class RPCReplicaProxy:
    """Client half: duck-types the replica surface off cached scrape
    snapshots.  ``summary()``/``add_request`` are live round trips (the
    two calls the Router needs); ``step_or_raise`` drives the remote
    engine and refreshes the snapshot in the same round trip, so the
    fleet harness' per-replica driver threads keep the cached view
    current; ``refresh_stats()`` is the explicit pull FleetAggregator
    uses between steps."""

    def __init__(self, address, connect_timeout: float = 5.0):
        self.address = tuple(address)
        self._sock = socket.create_connection(self.address,
                                              timeout=connect_timeout)
        # steps may sit behind a cold compile — no read deadline
        self._sock.settimeout(None)
        self._lock = threading.Lock()
        self._prefix = None               # prefix state lives remotely
        self._timings: dict = {}
        self.request_stats: Dict[int, dict] = {}
        self.results: Dict[int, np.ndarray] = {}
        self._queue: tuple = ()
        self._handoffs: tuple = ()
        self.num_active = 0
        self.blocks_in_use = None
        self._has_work = False
        info = self._call("ping")
        self.batch_slots = int(info["batch_slots"])
        self._request_stats_cap = int(info["request_stats_cap"])
        self.model = SimpleNamespace(cfg=SimpleNamespace(
            vocab_size=int(info["vocab_size"])))
        self.refresh_stats()

    def _call(self, method: str, **fields):
        msg = {"m": method}
        msg.update(fields)
        with self._lock:
            _send_frame(self._sock, msg)
            resp = _recv_frame(self._sock)
        if resp is None:
            raise ConnectionError("replica RPC connection closed")
        if not resp.get("ok"):
            raise RuntimeError(f"replica RPC failed: {resp.get('e')}")
        return resp.get("r")

    def _apply(self, snap: dict) -> None:
        self._timings = snap["timings"]
        self.request_stats.update(
            {int(k): v for k, v in snap["request_stats"].items()})
        self.results.update(
            {int(k): np.asarray(v, np.int32)
             for k, v in snap["results"].items()})
        self._apply_load(snap)

    def _apply_load(self, snap: dict) -> None:
        self._queue = (None,) * int(snap["queue_len"])
        self._handoffs = (None,) * int(snap.get("handoffs", 0))
        self.num_active = int(snap["num_active"])
        self.blocks_in_use = snap["blocks_in_use"]
        self._has_work = bool(snap["has_work"])

    def refresh_stats(self) -> None:
        self._apply(self._call("scrape"))

    def prefix_summary(self) -> Optional[dict]:
        summ = self._call("summary")
        if summ is None:
            return None
        summ["fingerprints"] = set(summ["fingerprints"])
        return summ

    def add_request(self, prompt, **kw) -> int:
        kw = {k: v for k, v in kw.items() if v is not None}
        return int(self._call(
            "add_request", prompt=np.asarray(prompt).tolist(), kw=kw))

    @property
    def has_work(self) -> bool:
        self._apply_load(self._call("load"))
        return self._has_work

    def step_or_raise(self) -> int:
        out = self._call("step")
        self._apply(out["snap"])
        return int(out["produced"])

    def step(self) -> int:
        return self.step_or_raise()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:  # pragma: no cover
            pass
