"""Paged KV cache: a fixed block pool + per-slot block tables.

The PR-4 serving engine preallocates a DENSE per-slot cache
``[layers, batch_slots, max_seq, kv_heads, head_dim]`` — every slot
owns ``max_seq`` positions whether it uses them or not, so slot count
(= concurrent users) is capped by ``slots × max_seq`` memory even when
every live request is short.  This module is the vLLM-style fix
(Kwon et al., *Efficient Memory Management for Large Language Model
Serving with PagedAttention*): K/V live in a pool of fixed-size blocks

    ``[layers, num_blocks, block_size, kv_heads, head_dim]``

and each slot holds a small BLOCK TABLE of pool indices.  A slot
consumes exactly ``ceil(len/block_size)`` blocks, so concurrency is
bounded by total memory, not by the worst-case sequence length — and
blocks can be SHARED between slots (refcounts), which is what makes
radix prefix caching (prefix_cache.py) free.

Split of responsibilities, mirroring the reference framework's
AllocatorFacade layer (PAPER.md §1 layer 1 — allocator policy lives
outside the kernels):

- **Device** (:class:`PagedKVCache`): the k/v pools only.  Statically
  shaped; every update inside the prefill/decode executables is a
  ``dynamic_update_slice``/scatter, so the zero-recompile invariant of
  the dense engine survives paging.  Registered as a pytree so it rides
  jit carries and donation.
- **Host** (:class:`BlockAllocator`): free-list + per-block refcounts.
  Block 0 is reserved as the NULL block — unused block-table entries
  point at it, so the executables never see an out-of-range index;
  whatever garbage lands there is masked by per-slot lengths.

Block tables and per-slot lengths stay host-side (numpy) and enter the
executables as ordinary ``[batch_slots, max_blocks]`` / ``[batch_slots]``
int32 operands each step: their shapes never change, and shipping a few
hundred int32s per step is noise next to the cache itself.
"""
from __future__ import annotations

import itertools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import metrics as _metrics

__all__ = ["PagedKVCache", "BlockAllocator", "init_paged_cache",
           "blocks_for", "blocks_to_extend"]


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` positions."""
    return -(-int(n_tokens) // int(block_size))


def blocks_to_extend(have_blocks: int, new_len: int,
                     block_size: int) -> int:
    """Additional blocks a slot holding ``have_blocks`` needs to cover
    ``new_len`` positions — the chunk-granular ensure-room arithmetic:
    a chunked prefill (and a multi-token spec commit) grows a slot by
    several tokens at once, so room is a delta in BLOCKS, not a
    yes/no on one."""
    return max(blocks_for(new_len, block_size) - int(have_blocks), 0)


class PagedKVCache:
    """Device half of the paged cache: ``k``/``v`` are
    ``[layers, num_blocks, block_size, kv_heads, head_dim]`` block
    pools.  Which blocks belong to which slot is the host allocator's
    business; the executables receive block tables as operands.

    Quantized form (``kv_dtype='int8'``/``'fp8'``): the value pools
    hold 8-bit values and ``k_scale``/``v_scale`` the per-(position,
    head) f32 scale pools ``[layers, num_blocks, block_size, kv_heads]``
    — the paged decode kernel streams both and dequantizes in VMEM.
    Full-precision pools (``k_scale is None``) stay the default and the
    parity oracle."""

    __slots__ = ("k", "v", "k_scale", "v_scale")

    def __init__(self, k, v, k_scale=None, v_scale=None):
        self.k, self.v = k, v
        self.k_scale, self.v_scale = k_scale, v_scale

    @property
    def num_layers(self):
        return self.k.shape[0]

    @property
    def num_blocks(self):
        return self.k.shape[1]

    @property
    def block_size(self):
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None

    def __repr__(self):
        return (f"PagedKVCache(layers={self.k.shape[0]}, "
                f"blocks={self.k.shape[1]}, block_size={self.k.shape[2]}, "
                f"kv_heads={self.k.shape[3]}, dtype={self.k.dtype}"
                f"{', quantized' if self.quantized else ''})")


jax.tree_util.register_pytree_node(
    PagedKVCache,
    lambda c: ((c.k, c.v, c.k_scale, c.v_scale), None),
    lambda aux, ch: PagedKVCache(*ch))


def init_paged_cache(model, num_blocks: int, block_size: int,
                     dtype=None, kv_dtype=None) -> PagedKVCache:
    """Allocate the zeroed block pool for ``model`` (a GPTForCausalLM /
    GPTModel).  ``num_blocks`` INCLUDES the reserved null block 0, so
    the usable capacity is ``num_blocks - 1`` blocks.  ``kv_dtype=
    'int8'``/``'fp8'`` (default from ``PADDLE_TPU_KV_DTYPE``) allocates
    8-bit value pools plus f32 scale pools."""
    from ..ops.quantized_matmul import kv_storage_dtype, resolve_kv_quant
    gpt = getattr(model, "gpt", model)
    cfg = gpt.cfg
    mode = resolve_kv_quant(kv_dtype)
    dt = kv_storage_dtype(mode) if mode else \
        (dtype or gpt.wte.weight.dtype)
    shape = (cfg.num_layers, int(num_blocks), int(block_size),
             cfg.num_kv_heads, cfg.head_dim)
    scales = (jnp.zeros(shape[:-1], jnp.float32),
              jnp.zeros(shape[:-1], jnp.float32)) if mode else (None, None)
    return PagedKVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt),
                        *scales)


class BlockAllocator:
    """Host-side pool bookkeeping: LIFO free-list + refcounts.

    Block ids run ``1..num_blocks-1`` (0 is the null block and is never
    handed out).  ``alloc`` refuses rather than over-commits — the
    scheduler turns a refusal into queueing/eviction/preemption, which
    is the whole point of admission-by-free-blocks.  ``incref`` is how
    a second owner (another slot sharing a prefix, or the radix cache
    pinning a node) holds a block; ``decref`` frees at zero.
    """

    _ids = itertools.count()

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError("need at least 2 blocks (block 0 is the "
                             "reserved null block)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._refs = np.zeros(self.num_blocks, np.int32)
        # LIFO: recently-freed blocks are re-used first (their pool rows
        # are warm in cache on CPU; harmless on TPU)
        self._free: List[int] = list(range(self.num_blocks - 1, 0, -1))
        # alloc-attempt counter (successes AND refusals): the scheduler
        # contract that a blocked head-of-line request is NOT re-probed
        # every tick is asserted against this number
        self.probes = 0
        # pool pressure into the metrics registry (one gauge set per
        # alloc/decref — attribute arithmetic on a pre-bound child)
        pool = f"p{next(BlockAllocator._ids)}"
        self._m_in_use = _metrics.gauge(
            "kv_blocks_in_use", "paged KV blocks held",
            labels=("pool",)).labels(pool=pool)
        _metrics.gauge("kv_blocks_capacity", "allocatable pool blocks",
                       labels=("pool",)).labels(pool=pool).set(
            self.capacity)

    @property
    def capacity(self) -> int:
        """Allocatable blocks (null block excluded)."""
        return self.num_blocks - 1

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_in_use(self) -> int:
        return self.capacity - len(self._free)

    def refcount(self, block: int) -> int:
        return int(self._refs[block])

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh blocks at refcount 1, or None when the pool cannot
        satisfy the request (caller queues/evicts/preempts)."""
        self.probes += 1
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        self._m_in_use.set(self.capacity - len(self._free))
        return out

    def incref(self, blocks) -> None:
        for b in blocks:
            if self._refs[b] <= 0:
                raise RuntimeError(f"incref on free block {b}")
            self._refs[b] += 1

    def decref(self, blocks) -> None:
        for b in blocks:
            r = int(self._refs[b]) - 1
            if r < 0:
                raise RuntimeError(f"double free of block {b}")
            self._refs[b] = r
            if r == 0:
                self._free.append(b)
        self._m_in_use.set(self.capacity - len(self._free))

    def check_leak_free(self) -> None:
        """Raise unless every block is back on the free list — the
        drain invariant the load-test smoke asserts."""
        if self.num_free != self.capacity:
            held = [b for b in range(1, self.num_blocks)
                    if self._refs[b] > 0]
            raise AssertionError(
                f"block pool leak: {self.num_free}/{self.capacity} free; "
                f"held blocks {held[:16]}{'...' if len(held) > 16 else ''}")
