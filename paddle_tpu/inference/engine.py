"""High-throughput serving engine: two executables + continuous batching.

The training side of this repo got its fast path in PRs 1-3 (fused
kernels, async dispatch, persistent compile cache); this module is the
same discipline for inference, built from two papers:

- Pope et al., *Efficiently Scaling Transformer Inference*: ONE compiled
  **prefill** executable per prompt-length bucket writing into a
  statically-shaped, preallocated KV cache
  (``models.gpt.StaticKVCache``, layout
  ``[layers, batch_slots, max_seq, kv_heads, head_dim]``), and ONE
  compiled **decode** executable appending a single token per slot and
  running the fused single-token attention kernel
  (``ops.decode_attention``) over the cache.  Nothing in the decode loop
  ever changes shape, so generating N tokens costs ZERO new XLA
  compiles (the contract ``bench.py --serve --smoke`` and
  tests/test_inference_engine.py assert via utils.compile_counter).
- Yu et al., *Orca*: **continuous batching** — the decode batch is a set
  of fixed ``batch_slots``; new requests are admitted into free slots
  BETWEEN decode steps (a prefill touches only its slot's cache rows),
  and finished requests retire their slot immediately instead of making
  short requests wait for the longest one in a static batch.

Sampling (greedy / temperature / top-k / top-p) runs inside the decode
executable, so each step costs exactly one host read-back — the sampled
token ids the scheduler needs for EOS retirement and admission (counted
by distributed.async_dispatch's host-sync counter, same as training).

Both executables go through the persistent XLA compile cache
(utils.compile_cache), so a server restart deserializes instead of
recompiling.  On the CPU backend the engine does NOT donate its cache
operands: jaxlib 0.4.x mis-aliases donated buffers in executables
deserialized from the persistent cache (the same hazard PR 2 hit with
rollback) — the compile-cache guard plus no-donation keeps the test
suite's warm cache safe.  On TPU, donation is on and the cache updates
are true in-place writes.

Knobs: ``PADDLE_TPU_DECODE_SLOTS`` (default 8) and
``PADDLE_TPU_PREFILL_BUCKETS`` (comma-separated lengths; default powers
of two up to max_seq_len).
"""
from __future__ import annotations

import itertools
import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..distributed import async_dispatch
from ..func import functional_apply, functional_state
from ..utils import compile_cache, compile_counter

__all__ = ["InferenceEngine", "Request", "default_prefill_buckets"]


def default_prefill_buckets(max_seq_len: int, lo: int = 16) -> List[int]:
    """Powers of two in [lo, max_seq_len], always including max_seq_len.
    ``PADDLE_TPU_PREFILL_BUCKETS="64,256,1024"`` overrides."""
    env = os.environ.get("PADDLE_TPU_PREFILL_BUCKETS", "").strip()
    if env:
        bks = sorted({int(x) for x in env.split(",") if x.strip()})
    else:
        bks = []
        b = lo
        while b < max_seq_len:
            bks.append(b)
            b *= 2
        bks.append(max_seq_len)
    return [b for b in bks if b <= max_seq_len] or [max_seq_len]


class Request:
    """One in-flight generation request (host-side bookkeeping)."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_id, temperature, top_p):
        self.rid = next(Request._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = -1 if eos_id is None else int(eos_id)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        self.done = False


class InferenceEngine:
    """Continuous-batching serving engine for GPTForCausalLM.

    Usage::

        eng = InferenceEngine(model, batch_slots=8)
        rid = eng.add_request(prompt_ids, max_new_tokens=64, eos_id=eos)
        outputs = eng.run()          # {rid: np.int32 generated tokens}

    or incrementally: ``eng.step()`` admits queued requests into free
    slots and decodes one token for every active slot; finished
    requests appear in ``eng.results``.
    """

    def __init__(self, model, batch_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 cache_dtype=None, top_k: int = 0, seed: int = 0,
                 mesh=None, donate: Optional[bool] = None):
        model.eval()
        self.model = model
        cfg = model.cfg
        self.batch_slots = int(batch_slots or
                               os.environ.get("PADDLE_TPU_DECODE_SLOTS", 8))
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"position table ({cfg.max_seq_len})")
        self.buckets = sorted(prefill_buckets or
                              default_prefill_buckets(self.max_seq_len))
        self.top_k = int(top_k)

        # persistent compile cache: a restarted server deserializes its
        # prefill/decode executables instead of recompiling them
        compile_cache.ensure_compile_cache()
        compile_counter.install()

        self.params, _ = functional_state(model)
        self.cache = model.init_kv_cache(self.batch_slots,
                                         self.max_seq_len, cache_dtype)
        self.mesh = mesh
        if mesh is not None:
            self._shard_over_mesh(mesh)

        # CPU + persistent cache + donation = the PR 2 mis-alias hazard
        # (deserialized executables alias donated buffers wrongly on
        # jaxlib 0.4.x CPU); see module docstring
        if donate is None:
            env = os.environ.get("PADDLE_TPU_INFER_DONATE")
            if env is not None:
                donate = env != "0"
            else:
                donate = jax.default_backend() not in ("cpu",)
        self._donate = bool(donate)
        # donation + CPU + persistent cache: never DESERIALIZE these
        # executables (compile fresh; entries still written) — see
        # compile_cache.suspend_cpu_cache_hits
        self._suspend_cache_hits = (self._donate and
                                    jax.default_backend() == "cpu")
        dargs = (1,) if self._donate else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=dargs)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=dargs)
        self._sample_jit = jax.jit(self._sample_from_logits)

        self._key = jax.random.PRNGKey(int(seed))

        # scheduler state
        self._queue: deque = deque()
        self._slots: List[Optional[Request]] = [None] * self.batch_slots
        self._next_token = np.zeros(self.batch_slots, np.int32)
        self._slot_len = np.zeros(self.batch_slots, np.int64)
        self._temps = np.zeros(self.batch_slots, np.float32)
        self._top_ps = np.ones(self.batch_slots, np.float32)
        self.results: Dict[int, np.ndarray] = {}

        # stats machinery (same shape as SpmdTrainer._timings/stats)
        self._timings = {
            "prefill_ms": 0.0, "decode_ms": 0.0, "sync_ms": 0.0,
            "compile_ms_cold": 0.0, "prefills": 0, "decode_steps": 0,
            "tokens_generated": 0, "occupancy_sum": 0.0,
        }
        self._first_call_keys: set = set()
        self._counters0 = compile_counter.snapshot()

    # ---- sharding -----------------------------------------------------
    def _shard_over_mesh(self, mesh):
        """Place the cache like a training activation: batch_slots over
        'dp', kv heads over 'tp' when those axes exist (best-effort —
        a 1-device mesh or missing axes degrade to replicated)."""
        try:
            from jax.sharding import NamedSharding, PartitionSpec as P
            names = mesh.axis_names
            dp = "dp" if "dp" in names and mesh.shape["dp"] > 1 else None
            tp = "tp" if "tp" in names and mesh.shape["tp"] > 1 else None
            kv_spec = NamedSharding(mesh, P(None, dp, None, tp, None))
            len_spec = NamedSharding(mesh, P(dp))
            self.cache = type(self.cache)(
                jax.device_put(self.cache.k, kv_spec),
                jax.device_put(self.cache.v, kv_spec),
                jax.device_put(self.cache.lengths, len_spec))
        except Exception:  # sharding is an optimization, never fatal
            pass

    # ---- compiled functions -------------------------------------------
    def _prefill_fn(self, params, cache, ids, slot, prompt_len):
        return functional_apply(self.model, "prefill", params,
                                ids, cache, slot, prompt_len)

    def _sample_from_logits(self, logits, key, temps, top_ps):
        """Greedy when temps<=0, else temperature + (static) top-k +
        (per-slot) top-p sampling. logits [N, V] f32."""
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        v = logits.shape[-1]
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        if self.top_k and self.top_k < v:
            kth = jax.lax.top_k(scaled, self.top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        # top-p in sorted space: keep tokens whose PRECEDING cumulative
        # mass is < p (the first token always survives)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        s_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(s_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        s_logits = jnp.where(csum - probs < top_ps[:, None],
                             s_logits, -1e30)
        choice = jax.random.categorical(key, s_logits, axis=-1)
        sampled = jnp.take_along_axis(
            sort_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy)

    def _decode_fn(self, params, cache, tokens, active, key, temps,
                   top_ps):
        logits, cache = functional_apply(self.model, "decode_step",
                                         params, tokens, cache, active)
        key, sub = jax.random.split(key)
        nxt = self._sample_from_logits(logits, sub, temps, top_ps)
        return nxt, key, cache

    # ---- timing helpers -----------------------------------------------
    def _timed(self, kind, key, fn):
        t0 = time.perf_counter()
        if key not in self._first_call_keys:
            # first call per executable = trace + compile/deserialize
            self._first_call_keys.add(key)
            if self._suspend_cache_hits:
                with compile_cache.suspend_cpu_cache_hits():
                    out = fn()
            else:
                out = fn()
            self._timings["compile_ms_cold"] += \
                (time.perf_counter() - t0) * 1e3
        else:
            out = fn()
            self._timings[kind] += (time.perf_counter() - t0) * 1e3
        return out

    # ---- public API ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int = 32,
                    eos_id: Optional[int] = None,
                    temperature: float = 0.0, top_p: float = 1.0) -> int:
        """Queue a generation request; returns its id. Admitted into a
        free slot at the next step()."""
        req = Request(prompt, max_new_tokens, eos_id, temperature, top_p)
        if req.prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds the largest "
                f"prefill bucket ({self.buckets[-1]})")
        if req.prompt.size >= self.max_seq_len:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens leaves no room to "
                f"generate within max_seq_len={self.max_seq_len}")
        self._queue.append(req)
        return req.rid

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _admit(self, req: Request, slot: int):
        bucket = self._bucket_for(req.prompt.size)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :req.prompt.size] = req.prompt
        plen = req.prompt.size
        logits, cache = self._timed(
            "prefill_ms", ("prefill", bucket), lambda: self._prefill_jit(
                self.params, self.cache, jnp.asarray(ids),
                np.int32(slot), np.int32(plen)))
        self.cache = cache
        # first generated token comes from the prefill logits
        self._key, sub = jax.random.split(self._key)
        # np (not list) literals: a python-float list would lower an
        # extra convert_element_type executable on the admission path
        tok = self._timed(
            "prefill_ms", ("sample", 1), lambda: self._sample_jit(
                logits, sub,
                np.asarray([req.temperature], np.float32),
                np.asarray([req.top_p], np.float32)))
        tok = int(np.asarray(tok)[0])
        async_dispatch.record_host_sync()
        self._timings["prefills"] += 1
        req.slot = slot
        self._slots[slot] = req
        self._slot_len[slot] = plen
        self._temps[slot] = req.temperature
        self._top_ps[slot] = req.top_p
        req.generated.append(tok)
        self._next_token[slot] = tok
        self._retire_if_done(req, tok)

    def _retire_if_done(self, req: Request, last_tok: int):
        """EOS / max-new-tokens / capacity retirement; frees the slot."""
        slot = req.slot
        full = self._slot_len[slot] + 1 >= self.max_seq_len
        if (last_tok == req.eos_id
                or len(req.generated) >= req.max_new_tokens or full):
            req.done = True
            self.results[req.rid] = np.asarray(req.generated, np.int32)
            self._slots[slot] = None
            self._temps[slot] = 0.0
            self._top_ps[slot] = 1.0
            req.slot = None

    @property
    def num_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    def step(self) -> int:
        """Admit queued requests into free slots, then decode one token
        for every active slot. Returns the number of tokens produced
        this step (admission prefills included)."""
        produced = 0
        for slot, occ in enumerate(self._slots):
            if occ is None and self._queue:
                # each admission produces its first token from the
                # prefill logits
                self._admit(self._queue.popleft(), slot)
                produced += 1
        active_np = np.asarray(
            [1 if r is not None else 0 for r in self._slots], np.int32)
        if not active_np.any():
            return produced
        self._timings["occupancy_sum"] += float(active_np.mean())
        nxt, self._key, cache = self._timed(
            "decode_ms", ("decode", 0), lambda: self._decode_jit(
                self.params, self.cache, jnp.asarray(self._next_token),
                jnp.asarray(active_np), self._key,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps)))
        self.cache = cache
        # the ONE host sync of the decode step: the scheduler needs the
        # sampled ids for EOS retirement and admission
        t0 = time.perf_counter()
        nxt_np = np.asarray(nxt)
        async_dispatch.record_host_sync()
        self._timings["sync_ms"] += (time.perf_counter() - t0) * 1e3
        self._timings["decode_steps"] += 1
        for slot, req in enumerate(self._slots):
            if req is None:
                continue
            tok = int(nxt_np[slot])
            self._slot_len[slot] += 1        # the token we just appended
            req.generated.append(tok)
            self._next_token[slot] = tok
            produced += 1
            self._timings["tokens_generated"] += 1
            self._retire_if_done(req, tok)
        return produced

    def run(self) -> Dict[int, np.ndarray]:
        """Drive step() until every queued request finished; returns
        {request_id: generated token ids}."""
        while self._queue or self.num_active:
            self.step()
        return self.results

    def warmup(self, buckets: Optional[List[int]] = None):
        """Compile (or deserialize from the persistent cache) the decode
        + sampling executables and the given prefill buckets before
        traffic arrives.  Uses slot 0 with throwaway tokens; the cache
        lengths are reset afterwards so the garbage stays masked."""
        assert self.num_active == 0 and not self._queue, \
            "warmup() must run before traffic"
        for b in (buckets or [self.buckets[0]]):
            ids = jnp.zeros((1, b), jnp.int32)
            logits, cache = self._timed(
                "prefill_ms", ("prefill", b), lambda: self._prefill_jit(
                    self.params, self.cache, ids, np.int32(0),
                    np.int32(1)))
            self.cache = cache
        self._key, sub = jax.random.split(self._key)
        self._timed("prefill_ms", ("sample", 1), lambda: self._sample_jit(
            logits, sub, jnp.zeros((1,), jnp.float32),
            jnp.ones((1,), jnp.float32)))
        nxt, self._key, cache = self._timed(
            "decode_ms", ("decode", 0), lambda: self._decode_jit(
                self.params, self.cache,
                jnp.zeros(self.batch_slots, jnp.int32),
                jnp.zeros(self.batch_slots, jnp.int32), self._key,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps)))
        # drop the warmup garbage: zero every slot's length (host-side
        # constant, so no extra executable rides the hot path)
        self.cache = type(cache)(cache.k, cache.v,
                                 jnp.zeros((self.batch_slots,), jnp.int32))
        return self

    @property
    def stats(self) -> dict:
        """Cumulative serving stats (SpmdTrainer.stats convention):
        prefill/decode wall-clock, compile_ms_cold (first call per
        executable), host sync time, tokens/sec over decode wall-clock,
        mean slot occupancy, and the process-wide XLA compile/trace
        deltas since engine construction."""
        t = self._timings
        s = {k: (round(v, 3) if isinstance(v, float) else v)
             for k, v in t.items()}
        steps = max(t["decode_steps"], 1)
        s["slot_occupancy"] = round(t["occupancy_sum"] / steps, 4)
        decode_s = t["decode_ms"] / 1e3
        s["decode_tokens_per_sec"] = round(
            t["tokens_generated"] / decode_s, 2) if decode_s > 0 else None
        s["xla_compiles"] = self._counters0.new_compiles
        s["jaxpr_traces"] = self._counters0.new_traces
        s["compile_cache_dir"] = compile_cache.compile_cache_dir()
        s["batch_slots"] = self.batch_slots
        s["buckets"] = list(self.buckets)
        s["donate"] = self._donate
        return s
