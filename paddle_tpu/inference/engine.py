"""High-throughput serving engine: two executables + continuous batching.

The training side of this repo got its fast path in PRs 1-3 (fused
kernels, async dispatch, persistent compile cache); this module is the
same discipline for inference, built from three papers:

- Pope et al., *Efficiently Scaling Transformer Inference*: ONE compiled
  **prefill** executable per prompt-length bucket writing into a
  statically-shaped, preallocated KV cache, and ONE compiled **decode**
  executable appending a single token per slot and running the fused
  single-token attention kernel (``ops.decode_attention``) over the
  cache.  Nothing in the decode loop ever changes shape, so generating N
  tokens costs ZERO new XLA compiles (the contract ``bench.py --serve
  --smoke`` and the engine tests assert via utils.compile_counter).
- Yu et al., *Orca*: **continuous batching** — the decode batch is a set
  of fixed ``batch_slots``; new requests are admitted into free slots
  BETWEEN decode steps, and finished requests retire their slot
  immediately instead of making short requests wait for the longest one
  in a static batch.
- Kwon et al., *PagedAttention* (vLLM): with ``kv_layout='paged'`` the
  cache is a BLOCK POOL (``inference.paged_kv.PagedKVCache``) and each
  slot holds a block table, so a slot consumes memory proportional to
  its ACTUAL length — admission is by free-block count, not free slots,
  and short requests no longer strand ``max_seq`` rows each.  A radix
  prefix cache (``inference.prefix_cache``) shares prompt-prefix blocks
  between requests so common system prompts prefill once; on pool
  exhaustion the scheduler first evicts unpinned cache blocks, then
  PREEMPTS the youngest request back onto the queue (it resumes later
  via a prefill over prompt+generated — which usually hits the radix
  cache) instead of deadlocking.  ``kv_layout='dense'`` (default) keeps
  the PR-4 ``StaticKVCache`` and is the parity oracle for the paged
  path.

Sampling (greedy / temperature / top-k / top-p) runs inside the decode
executable, so each step costs exactly one host read-back — the sampled
token ids the scheduler needs for EOS retirement and admission (counted
by distributed.async_dispatch's host-sync counter, same as training).

Both executables go through the persistent XLA compile cache
(utils.compile_cache), so a server restart deserializes instead of
recompiling.  On the CPU backend the engine does NOT donate its cache
operands: jaxlib 0.4.x mis-aliases donated buffers in executables
deserialized from the persistent cache (the same hazard PR 2 hit with
rollback) — the compile-cache guard plus no-donation keeps the test
suite's warm cache safe.  On TPU, donation is on and the cache updates
are true in-place writes.

Chunked prefill (ISSUE 20, Agrawal et al., *Sarathi-Serve*): the
monolithic bucketed prefill above runs BETWEEN decode ticks, so one
long admission freezes every in-flight stream — the classic
prefill/decode interference.  ``PADDLE_TPU_CHUNKED_PREFILL=<chunk>``
(engine kwarg ``prefill_chunk=``) switches admission to a token
budget: each tick advances every still-prefilling slot by up to
``chunk`` prompt tokens total through ONE fixed-shape chunk executable
(the PR-10 window-attention machinery with W = chunk), alongside —
never instead of — the decode batch.  A slot GRADUATES to decode when
its prompt completes; until then it is excluded from the decode/spec
active set.  Inter-token latency at the tail is bounded by the chunk
size instead of the longest prompt, throughput stays within noise
(same tokens, same executables count), and the zero-recompile
discipline survives because the chunk executable's shapes never
change.  Greedy output is token-identical to unchunked across
dense/paged × fp/int8 × GQA.

Knobs: ``PADDLE_TPU_DECODE_SLOTS`` (default 8),
``PADDLE_TPU_PREFILL_BUCKETS`` (comma-separated lengths; default powers
of two up to max_seq_len), ``PADDLE_TPU_KV_LAYOUT`` (dense|paged),
``PADDLE_TPU_KV_BLOCK_SIZE`` (default 128), ``PADDLE_TPU_KV_BLOCKS``
(usable pool blocks; default = dense-equivalent memory),
``PADDLE_TPU_PREFIX_CACHE`` (default on for paged),
``PADDLE_TPU_CHUNKED_PREFILL`` (chunk size; 0 = monolithic prefill,
the default), and ``PADDLE_TPU_KV_DTYPE`` (int8|fp8; quantized KV
storage with per-head scales dequantized inside the decode kernels —
half the HBM bytes per step; default full precision).
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..distributed import async_dispatch
from ..distributed import moe as _moe
from ..func import functional_apply, functional_state
from ..observability import capture as _capture
from ..observability import doctor as _doctor
from ..observability import exec_registry as _exec_registry
from ..observability import flightrec as _flightrec
from ..observability import metrics as _metrics
from ..observability import spans as _spans
from ..observability import watchdog as _watchdog
from ..utils import compile_cache, compile_counter
from .paged_kv import (BlockAllocator, blocks_for, blocks_to_extend,
                       init_paged_cache)
from .prefix_cache import RadixPrefixCache

__all__ = ["InferenceEngine", "Request", "default_prefill_buckets"]


def default_prefill_buckets(max_seq_len: int, lo: int = 16) -> List[int]:
    """Powers of two in [lo, max_seq_len], always including max_seq_len.
    ``PADDLE_TPU_PREFILL_BUCKETS="64,256,1024"`` overrides; between the
    env and the powers-of-two default sits the unified tuning table
    (utils.tuning, op "prefill_buckets", key (device_kind, max_seq_len))
    so a bucket list tuned for a traffic mix persists across restarts."""
    env = os.environ.get("PADDLE_TPU_PREFILL_BUCKETS", "").strip()
    if env:
        bks = sorted({int(x) for x in env.split(",") if x.strip()})
    else:
        bks = None
        try:
            from ..utils import tuning as _tuning
            tuned = _tuning.lookup("prefill_buckets",
                                   (_tuning.device_kind(), max_seq_len))
            if tuned:
                bks = sorted({int(x) for x in tuned})
        except (ValueError, TypeError):
            pass
        if not bks:
            bks = []
            b = lo
            while b < max_seq_len:
                bks.append(b)
                b *= 2
            bks.append(max_seq_len)
    return [b for b in bks if b <= max_seq_len] or [max_seq_len]


class Request:
    """One in-flight generation request (host-side bookkeeping)."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens, eos_id, temperature, top_p,
                 deadline_s: Optional[float] = None):
        self.rid = next(Request._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = -1 if eos_id is None else int(eos_id)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        self.generated: List[int] = []
        self.slot: Optional[int] = None
        self.done = False
        # per-request deadline (absolute perf_counter time): a request
        # past it is RETIRED — slot + blocks freed, partial tokens
        # delivered, record flagged timed_out — instead of occupying a
        # decode slot (or the queue) forever
        self.deadline: Optional[float] = None \
            if deadline_s is None \
            else time.perf_counter() + float(deadline_s)
        self.timed_out = False
        # per-request latency accounting (stats / load harness)
        self.t_enqueue = time.perf_counter()
        self.t_admit: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_finish: Optional[float] = None
        # decode wall-clock summed over ACTIVATIONS only (a preempted
        # request's requeue wait must not dilute its decode tok/s),
        # and queue wait summed over WAITS only (symmetrically, active
        # decode time must not inflate queued_ms)
        self.active_s = 0.0
        self.t_live: Optional[float] = None
        self.queued_s = 0.0
        self.t_queue_since = self.t_enqueue
        # preemption support: a preempted request resumes via a prefill
        # over prompt+generated-so-far (this field), keeping `generated`
        self.resume_prompt: Optional[np.ndarray] = None
        self.preemptions = 0
        self.admit_seq: Optional[int] = None
        # chunked prefill (ISSUE 20): a slot holds its request while
        # the prompt prefills chunk by chunk; `prefill_pos` is how many
        # prompt tokens are in the cache, `prefilling` keeps the slot
        # out of the decode/spec active set until graduation
        self.prefill_pos = 0
        self.prefilling = False
        # per-token delivery timestamps (first token + every commit):
        # the inter-token-latency record the load harness pools
        self.token_times: List[float] = []

    def effective_prompt(self) -> np.ndarray:
        return self.prompt if self.resume_prompt is None \
            else self.resume_prompt


class InferenceEngine:
    """Continuous-batching serving engine for GPTForCausalLM.

    Telemetry (ISSUE 13): every engine feeds the process metrics
    registry (labeled ``engine=eN``) and, when the span tracer is armed,
    emits the per-request lifecycle timeline — ``queued`` → ``prefill``
    → ``decode`` spans on a per-request track plus per-tick spans
    (preemptions as instants, speculative accept counts as tick args).
    All of it is host-side timestamp arithmetic: telemetry adds ZERO
    host syncs per tick and never perturbs executable shapes
    (zero-recompile preserved — proven in tests/test_telemetry.py).

    Usage::

        eng = InferenceEngine(model, batch_slots=8, kv_layout="paged")
        rid = eng.add_request(prompt_ids, max_new_tokens=64, eos_id=eos)
        outputs = eng.run()          # {rid: np.int32 generated tokens}

    or incrementally: ``eng.step()`` admits queued requests into free
    slots and decodes one token for every active slot; finished
    requests appear in ``eng.results``.  ``eng.generate(prompt)`` is the
    blocking single-request form: it goes through the same admission
    queue, so on a full engine it WAITS for capacity instead of raising.
    """

    _engine_ids = itertools.count()

    def __init__(self, model, batch_slots: Optional[int] = None,
                 max_seq_len: Optional[int] = None,
                 prefill_buckets: Optional[List[int]] = None,
                 cache_dtype=None, top_k: int = 0, seed: int = 0,
                 mesh=None, donate: Optional[bool] = None,
                 kv_layout: Optional[str] = None,
                 kv_block_size: Optional[int] = None,
                 kv_num_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_dtype: Optional[str] = None,
                 spec_k: Optional[int] = None, draft_model=None,
                 prefill_chunk: Optional[int] = None):
        model.eval()
        self.model = model
        cfg = model.cfg
        self.batch_slots = int(batch_slots or
                               os.environ.get("PADDLE_TPU_DECODE_SLOTS", 8))
        self.max_seq_len = int(max_seq_len or cfg.max_seq_len)
        if self.max_seq_len > cfg.max_seq_len:
            raise ValueError(
                f"max_seq_len {self.max_seq_len} exceeds the model's "
                f"position table ({cfg.max_seq_len})")
        self.buckets = sorted(prefill_buckets or
                              default_prefill_buckets(self.max_seq_len))
        self.top_k = int(top_k)
        self.kv_layout = (kv_layout or
                          os.environ.get("PADDLE_TPU_KV_LAYOUT", "dense"))
        if self.kv_layout not in ("dense", "paged"):
            raise ValueError(f"kv_layout must be dense|paged, got "
                             f"{self.kv_layout!r}")
        # quantized KV storage ('int8'/'fp8'; env PADDLE_TPU_KV_DTYPE):
        # halves the bytes every decode step streams from HBM.  None =
        # full-precision cache, the default and the parity oracle.
        from ..ops.quantized_matmul import resolve_kv_quant
        self.kv_dtype = resolve_kv_quant(kv_dtype)
        # chunked prefill (ISSUE 20; env PADDLE_TPU_CHUNKED_PREFILL):
        # 0/unset keeps the monolithic bucketed admission prefill
        if prefill_chunk is None:
            env = os.environ.get("PADDLE_TPU_CHUNKED_PREFILL",
                                 "").strip()
            prefill_chunk = int(env) if env else 0
        self.prefill_chunk = int(prefill_chunk)
        if self.prefill_chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got "
                             f"{self.prefill_chunk}")
        self._chunked = self.prefill_chunk > 0

        # persistent compile cache: a restarted server deserializes its
        # prefill/decode executables instead of recompiling them
        compile_cache.ensure_compile_cache()
        compile_counter.install()

        self.params, _ = functional_state(model)
        # serving mesh (ISSUE 18): explicit arg, else PADDLE_TPU_SERVE_TP=N
        # builds a {"dp": 1, "tp": N} mesh.  Every serving executable then
        # compiles SPMD over it — weights column/row-split by the pspecs
        # the training-side parallel layers already mark, KV heads over
        # 'tp', dense batch slots over 'dp' — with no model-code changes:
        # GSPMD follows the committed operand shardings.
        if mesh is None:
            env_tp = os.environ.get("PADDLE_TPU_SERVE_TP", "").strip()
            # expert parallelism (ISSUE 19): PADDLE_TPU_SERVE_EP=N adds
            # an 'ep' axis — MoE expert FFN weights shard over it and
            # the MoE serving dispatch routes tokens with explicit
            # chunked all-to-all (distributed.moe._fn_serve_ep)
            env_ep = os.environ.get("PADDLE_TPU_SERVE_EP", "").strip()
            tp = int(env_tp) if env_tp else 1
            ep = int(env_ep) if env_ep else 1
            if tp > 1 or ep > 1:
                from ..distributed.mesh import create_mesh
                axes = {"dp": 1, "tp": tp}
                if ep > 1:
                    axes["ep"] = ep
                mesh = create_mesh(axes)
        self.mesh = mesh
        self.tp_degree = int(mesh.shape["tp"]) \
            if mesh is not None and "tp" in mesh.axis_names else 1
        self.ep_degree = int(mesh.shape["ep"]) \
            if mesh is not None and "ep" in mesh.axis_names else 1
        self._shard_warned = False
        if self.kv_layout == "paged":
            self._init_paged(cache_dtype, kv_block_size, kv_num_blocks,
                             prefix_cache)
        else:
            self.cache = model.init_kv_cache(self.batch_slots,
                                             self.max_seq_len, cache_dtype,
                                             kv_dtype=self.kv_dtype)
            self._alloc = None
            self._prefix = None
        if mesh is not None:
            self._shard_over_mesh(mesh)

        # CPU + persistent cache + donation = the PR 2 mis-alias hazard
        # (deserialized executables alias donated buffers wrongly on
        # jaxlib 0.4.x CPU); see module docstring
        if donate is None:
            env = os.environ.get("PADDLE_TPU_INFER_DONATE")
            if env is not None:
                donate = env != "0"
            else:
                donate = jax.default_backend() not in ("cpu",)
        self._donate = bool(donate)
        # donation + CPU + persistent cache: never DESERIALIZE these
        # executables (compile fresh; entries still written) — see
        # compile_cache.suspend_cpu_cache_hits
        self._suspend_cache_hits = (self._donate and
                                    jax.default_backend() == "cpu")
        dargs = (1,) if self._donate else ()
        self._prefill_jit = jax.jit(self._prefill_fn, donate_argnums=dargs)
        self._decode_jit = jax.jit(self._decode_fn, donate_argnums=dargs)
        self._prefill_paged_cold_jit = jax.jit(
            self._prefill_paged_cold_fn, donate_argnums=dargs)
        self._prefill_paged_ext_jit = jax.jit(
            self._prefill_paged_ext_fn, donate_argnums=dargs)
        self._decode_paged_jit = jax.jit(
            self._decode_paged_fn, donate_argnums=dargs)
        self._prefill_chunk_jit = jax.jit(
            self._prefill_chunk_fn, donate_argnums=dargs)
        self._prefill_chunk_paged_jit = jax.jit(
            self._prefill_chunk_paged_fn, donate_argnums=dargs)
        self._sample_jit = jax.jit(self._sample_from_logits)

        # speculative decoding (inference.spec_decode): a draft model +
        # K>0 replace the single-token decode step with a propose/verify
        # tick committing ~K+1 tokens per host sync.  Greedy slots use
        # the temperature-0 acceptance rule (token-identical to the
        # non-speculative rollout); temperature>0 slots run the full
        # rejection-sampling residual (ISSUE 18 satellite), so sampled
        # traffic rides the spec path too.
        from .spec_decode import SpecDecoder, resolve_spec_k
        sk = resolve_spec_k(spec_k)
        self._spec = None
        if sk > 0:
            if draft_model is None:
                raise ValueError(
                    "spec_k/PADDLE_TPU_SPEC_K set but no draft_model "
                    "given — speculation needs a draft (the target "
                    "model itself is a valid, if pointless-on-paper, "
                    "draft for harnesses)")
            self._spec = SpecDecoder(self, draft_model, sk)
        self.spec_k = self._spec.k if self._spec else 0

        self._key = jax.random.PRNGKey(int(seed))
        if self.mesh is not None:
            # commit the sampling key to the mesh (replicated) at init:
            # the steady-state key is a mesh-committed jit output, and a
            # host-resident warmup key would recompile every key
            # consumer (split/sample/decode) on the first real step —
            # the jit cache keys on committed-vs-uncommitted shardings
            try:
                self._key = self._put(self.mesh, self._key, (None,))
            except Exception as e:
                self._shard_failed("rng_key", e)

        # scheduler state
        self._queue: deque = deque()
        self._slots: List[Optional[Request]] = [None] * self.batch_slots
        self._next_token = np.zeros(self.batch_slots, np.int32)
        self._slot_len = np.zeros(self.batch_slots, np.int64)
        self._temps = np.zeros(self.batch_slots, np.float32)
        self._top_ps = np.ones(self.batch_slots, np.float32)
        self._admit_counter = itertools.count()
        # head-of-line admission memo (ISSUE 20 bugfix): once the queue
        # head fails paged admission, remember (rid, free-block count,
        # release epoch) and skip re-running the whole radix-match +
        # alloc dance every tick until blocks could actually have come
        # free — the epoch catches frees that don't change num_free
        # (a retirement whose blocks are all radix-pinned still makes
        # them EVICTABLE, which a pure free-count gate would miss)
        self._hol_block: Optional[tuple] = None
        self._release_epoch = 0
        # chunk-tick expert-stats folds parked until the next real host
        # sync (folding per chunk tick would add a sync per tick)
        self._moe_pending: List = []
        self.results: Dict[int, np.ndarray] = {}
        self.request_stats: Dict[int, dict] = {}
        self._request_stats_cap = 4096     # bounded per-request history
        self._results_cap = 65536          # results eviction safety net

        # stats machinery (same shape as SpmdTrainer._timings/stats)
        self._timings = {
            "prefill_ms": 0.0, "decode_ms": 0.0, "sync_ms": 0.0,
            # decode-tick wall time lost to monolithic admission
            # prefills while other streams sat waiting — the
            # interference signal the 'prefill-stall' doctor rule reads
            # (identically 0 in chunked mode, where admission never
            # stalls the decode batch)
            "prefill_stall_ms": 0.0,
            "compile_ms_cold": 0.0, "prefills": 0, "prefill_tokens": 0,
            "decode_steps": 0, "tokens_generated": 0,
            "occupancy_sum": 0.0, "block_occupancy_sum": 0.0,
            "preemptions": 0, "memory_capped_retirements": 0,
            "deadline_retirements": 0, "drain_forced_retirements": 0,
            "spec_ticks": 0, "spec_tokens_committed": 0,
            "spec_slot_ticks": 0, "spec_capacity_retirements": 0,
            "moe_assigned_tokens": 0.0, "moe_dropped_tokens": 0.0,
        }
        # expert-balance accumulators (ISSUE 19): the per-expert load
        # histogram summed over every executed step/prefill/tick, host
        # float64 so a long-lived server never loses counts to f32
        self._is_moe = int(getattr(cfg, "moe_num_experts", 0) or 0) > 0
        self._moe_load: Optional[np.ndarray] = None
        # graceful drain / preemption hookup (SIGTERM'd server finishes
        # what it started): while draining, admission is closed
        self._draining = False
        self._guard = None
        self._guard_timeout: Optional[float] = None
        self.undelivered: List[Request] = []
        self._first_call_keys: set = set()
        self._counters0 = compile_counter.snapshot()

        # unified telemetry (observability/): registry children bound
        # ONCE per engine (per-tick cost = attribute arithmetic), the
        # span tracer handle (gated on .active — one attr read when
        # off), and the PADDLE_TPU_PROFILE window keyed on decode ticks.
        self.telemetry_label = f"e{next(InferenceEngine._engine_ids)}"
        lbl = dict(engine=self.telemetry_label)
        # executable observatory + HBM ledger (ISSUE 15): every compiled
        # executable this engine builds joins the process registry under
        # this component label (see _timed_exec), and the resident state
        # — params, KV pool, draft cache — is tracked in the ledger
        # (host-side shape math, weakref'd to this engine so a retired
        # replica's pool drops out of the accounting)
        self._exec_component = f"engine:{self.telemetry_label}"
        _exec_registry.track_bytes(
            self, "params", self.telemetry_label,
            _exec_registry.tree_bytes(self.params))
        _exec_registry.track_bytes(
            self, "kv_cache", self.telemetry_label,
            _exec_registry.tree_bytes(self.cache),
            layout=self.kv_layout, kv_dtype=self.kv_dtype or "dense")
        if self._spec is not None:
            _exec_registry.track_bytes(
                self, "spec_draft", self.telemetry_label,
                _exec_registry.tree_bytes(self._spec.draft_params) +
                _exec_registry.tree_bytes(self._spec.draft_cache))
        if self._is_moe:
            # expert-parallel HBM win as a ledger line (ISSUE 19): the
            # "params" entry above is GLOBAL-shape math; this one is the
            # PER-DEVICE expert-weight residency, read off the committed
            # arrays' shard shapes — under ep>1 it drops ~ep× vs
            # replicated, and the acceptance test asserts exactly that
            _exec_registry.track_bytes(
                self, "moe_experts", self.telemetry_label,
                self._moe_expert_bytes_per_device(),
                ep=self.ep_degree,
                num_experts=int(cfg.moe_num_experts))
        self._tracer = _spans.tracer()
        self._profile = _capture.ProfileWindow.from_env(kind="serve")
        self._m_ticks = _metrics.counter(
            "serve_decode_ticks_total", "decode steps/ticks",
            labels=("engine",)).labels(**lbl)
        self._m_tokens = _metrics.counter(
            "serve_tokens_total", "generated tokens",
            labels=("engine",)).labels(**lbl)
        self._m_prefills = _metrics.counter(
            "serve_prefills_total", "admission prefills",
            labels=("engine",)).labels(**lbl)
        self._m_preempts = _metrics.counter(
            "serve_preemptions_total", "requests preempted to queue",
            labels=("engine",)).labels(**lbl)
        self._m_req_ok = _metrics.counter(
            "serve_requests_total", "finished requests",
            labels=("engine", "outcome")).labels(outcome="ok", **lbl)
        self._m_req_to = _metrics.counter(
            "serve_requests_total", "finished requests",
            labels=("engine", "outcome")).labels(outcome="timed_out",
                                                 **lbl)
        self._m_ttft = _metrics.histogram(
            "serve_ttft_ms", "enqueue -> first token",
            labels=("engine",)).labels(**lbl)
        self._m_queue = _metrics.gauge(
            "serve_queue_depth", "queued requests",
            labels=("engine",)).labels(**lbl)
        self._m_active = _metrics.gauge(
            "serve_active_slots", "occupied decode slots",
            labels=("engine",)).labels(**lbl)
        # flight recorder + stall watchdog (observability): crash hooks
        # once per process; the watchdog thread appears on the first
        # tick only when PADDLE_TPU_WATCHDOG_S arms it, and an engine
        # with no work parks it (an idle server is not a stall)
        _flightrec.install()
        self.watchdog: Optional[_watchdog.Watchdog] = None
        self._wd_checked = False
        # live autotune tier (PADDLE_TPU_AUTOTUNE=live): SLO-triggered,
        # quiesce-gated prefill-bucket retuner — None when unarmed, and
        # the tick hook below is a single attribute check
        from ..autotune.live import arm_engine as _arm_autotune
        self._retuner = _arm_autotune(self)

    # ---- paged layout setup -------------------------------------------
    def _init_paged(self, cache_dtype, kv_block_size, kv_num_blocks,
                    prefix_cache):
        """Block pool + allocator + host block tables + radix cache.
        Default pool size is DENSE-EQUIVALENT memory (batch_slots ×
        ceil(max_seq/bs) blocks) so the layouts A/B at equal footprint;
        real deployments size it to the HBM actually available
        (``PADDLE_TPU_KV_BLOCKS``)."""
        bs = int(kv_block_size or
                 os.environ.get("PADDLE_TPU_KV_BLOCK_SIZE", 128))
        if bs < 1:
            raise ValueError(f"kv_block_size must be >= 1, got {bs}")
        self.block_size = bs
        self._cache_dtype = cache_dtype   # disagg worker pool mirrors it
        self.blocks_per_slot = blocks_for(self.max_seq_len, bs)
        usable = int(kv_num_blocks or
                     os.environ.get("PADDLE_TPU_KV_BLOCKS", 0)) or \
            self.batch_slots * self.blocks_per_slot
        self.num_blocks = usable
        # +1: block 0 is the reserved null block unused table entries
        # point at (paged_kv module docstring)
        self.cache = init_paged_cache(self.model, usable + 1, bs,
                                      cache_dtype,
                                      kv_dtype=self.kv_dtype)
        self._alloc = BlockAllocator(usable + 1, bs)
        self._tables = np.zeros((self.batch_slots, self.blocks_per_slot),
                                np.int32)
        self._slot_blocks: List[List[int]] = \
            [[] for _ in range(self.batch_slots)]
        if prefix_cache is None:
            prefix_cache = os.environ.get("PADDLE_TPU_PREFIX_CACHE",
                                          "1") != "0"
        self._prefix = RadixPrefixCache(self._alloc, bs) \
            if prefix_cache else None

    # ---- sharding -----------------------------------------------------
    def _spec_for(self, mesh, arr, dims):
        """NamedSharding for ``arr`` from a per-dimension axis-name
        tuple.  A dimension degrades to replicated when the axis is
        missing from the mesh, has extent 1, or does not divide the
        array dimension (GSPMD would otherwise pad) — so every caller
        can name its IDEAL layout and let the mesh decide."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        out = []
        for d, ax in enumerate(dims):
            ok = (isinstance(ax, str) and ax in mesh.axis_names
                  and int(mesh.shape[ax]) > 1
                  and arr.shape[d] % int(mesh.shape[ax]) == 0)
            out.append(ax if ok else None)
        # canonical form: trailing Nones dropped.  GSPMD reports a
        # fully-replicated executable OUTPUT as P() — committing inputs
        # as P(None,...) would be semantically identical but a
        # DIFFERENT jit cache key, costing one spurious recompile on
        # the first post-warmup call whose operand came back from
        # another executable (seen on an ep-only mesh, where the KV
        # cache is fully replicated end to end)
        while out and out[-1] is None:
            out.pop()
        return NamedSharding(mesh, P(*out))

    def _put(self, mesh, arr, dims):
        return jax.device_put(arr, self._spec_for(mesh, arr, dims))

    def _shard_failed(self, what: str, err: Exception):
        """A mis-sharded pod must read as DEGRADED, not silently
        replicate (ISSUE 18 satellite): warn once per engine, count
        every failure in ``engine_sharding_failures_total``."""
        import warnings
        _metrics.counter(
            "engine_sharding_failures_total",
            "serving-state placements that fell back to replicated"
        ).inc()
        if not self._shard_warned:
            self._shard_warned = True
            warnings.warn(
                f"serving-mesh sharding failed for {what}: {err!r} — "
                f"the engine continues with replicated state (slower, "
                f"more HBM per device, never wrong)", RuntimeWarning,
                stacklevel=3)

    def _shard_params_over(self, mesh, params, module):
        """Commit a functional_state params dict to ``mesh`` by the
        pspecs the training-side parallel layers marked on their
        parameters (ColumnParallelLinear W: P(None,'tp'),
        RowParallelLinear W: P('tp',None), VocabParallelEmbedding:
        P('tp',None)); unmarked parameters replicate.  Committed
        weights are what makes every downstream jit compile SPMD —
        GSPMD follows the operands, no model-code changes."""
        marked = dict(module.named_parameters())
        out = {}
        for name, arr in params.items():
            pspec = getattr(marked.get(name), "pspec", None)
            dims = [None] * arr.ndim
            if pspec is not None:
                for d, ax in enumerate(tuple(pspec)[:arr.ndim]):
                    dims[d] = ax
            out[name] = self._put(mesh, arr, dims)
        return out

    def _shard_dense_cache_arrays(self, mesh, cache):
        """StaticKVCache layout on the mesh: k/v [L, B, S, Hkv, D] —
        batch slots over 'dp', KV heads over 'tp'; lengths follow the
        slots.  Returns a new cache of the same type."""
        scales = ()
        if cache.quantized:
            scales = (self._put(mesh, cache.k_scale,
                                (None, "dp", None, "tp")),
                      self._put(mesh, cache.v_scale,
                                (None, "dp", None, "tp")))
        return type(cache)(
            self._put(mesh, cache.k, (None, "dp", None, "tp", None)),
            self._put(mesh, cache.v, (None, "dp", None, "tp", None)),
            self._put(mesh, cache.lengths, ("dp",)),
            *scales)

    def _shard_paged_cache_arrays(self, mesh, cache):
        """Paged pool layout on the mesh: k/v [L, NB, bs, Hkv, D] —
        KV heads over 'tp', block/position dims REPLICATED so host-side
        allocation, the radix prefix cache and zero-recompile slot
        churn never see the mesh (block tables stay plain host int32)."""
        scales = ()
        if cache.quantized:
            scales = (self._put(mesh, cache.k_scale,
                                (None, None, None, "tp")),
                      self._put(mesh, cache.v_scale,
                                (None, None, None, "tp")))
        return type(cache)(
            self._put(mesh, cache.k, (None, None, None, "tp", None)),
            self._put(mesh, cache.v, (None, None, None, "tp", None)),
            *scales)

    def _shard_over_mesh(self, mesh):
        """Commit the engine's resident state (weights + KV cache) to
        the serving mesh.  Failures route through _shard_failed
        (warn-once + metric) instead of a silent pass: the engine still
        serves correct tokens replicated, but the operator can see it."""
        try:
            self.params = self._shard_params_over(mesh, self.params,
                                                  self.model)
        except Exception as e:
            self._shard_failed("params", e)
        try:
            if self.kv_layout == "paged":
                self.cache = self._shard_paged_cache_arrays(mesh,
                                                            self.cache)
            else:
                self.cache = self._shard_dense_cache_arrays(mesh,
                                                            self.cache)
        except Exception as e:
            self._shard_failed("kv_cache", e)

    # ---- compiled functions -------------------------------------------
    # Every model-running executable opens the MoE expert-stats
    # collector around its trace (ISSUE 19): MoE layers record their
    # per-expert dispatch load INSIDE the jitted program, the fold
    # rides out as one extra [num_experts]-sized output fetched at the
    # step's existing host sync — zero extra syncs, and a dense model
    # folds to None (an empty pytree leaf group), so non-MoE engines
    # compile byte-identical programs.
    def _prefill_fn(self, params, cache, ids, slot, prompt_len):
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(self.model, "prefill",
                                             params, ids, cache, slot,
                                             prompt_len)
        return logits, cache, _moe.fold_expert_stats(b)

    def _prefill_paged_cold_fn(self, params, cache, ids, table_row,
                               suffix_len):
        # prefix_len is a STATIC Python 0: the cold path compiles with
        # the exact flash/composite attention of the dense prefill
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(self.model, "prefill_paged",
                                             params, ids, cache,
                                             table_row, 0, suffix_len)
        return logits, cache, _moe.fold_expert_stats(b)

    def _prefill_paged_ext_fn(self, params, cache, ids, table_row,
                              prefix_len, suffix_len):
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(self.model, "prefill_paged",
                                             params, ids, cache,
                                             table_row, prefix_len,
                                             suffix_len)
        return logits, cache, _moe.fold_expert_stats(b)

    def _sample_from_logits(self, logits, key, temps, top_ps):
        """Greedy when temps<=0, else temperature + (static) top-k +
        (per-slot) top-p sampling. logits [N, V] f32."""
        logits = logits.astype(jnp.float32)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        v = logits.shape[-1]
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
        if self.top_k and self.top_k < v:
            kth = jax.lax.top_k(scaled, self.top_k)[0][:, -1:]
            scaled = jnp.where(scaled < kth, -1e30, scaled)
        # top-p in sorted space: keep tokens whose PRECEDING cumulative
        # mass is < p (the first token always survives)
        sort_idx = jnp.argsort(-scaled, axis=-1)
        s_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)
        probs = jax.nn.softmax(s_logits, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        s_logits = jnp.where(csum - probs < top_ps[:, None],
                             s_logits, -1e30)
        choice = jax.random.categorical(key, s_logits, axis=-1)
        sampled = jnp.take_along_axis(
            sort_idx, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)
        return jnp.where(temps > 0, sampled, greedy)

    def _decode_fn(self, params, cache, tokens, active, key, temps,
                   top_ps):
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(self.model, "decode_step",
                                             params, tokens, cache,
                                             active)
        key, sub = jax.random.split(key)
        nxt = self._sample_from_logits(logits, sub, temps, top_ps)
        return nxt, key, cache, _moe.fold_expert_stats(b)

    def _decode_paged_fn(self, params, cache, tokens, tables, lengths,
                         key, temps, top_ps):
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(self.model,
                                             "decode_step_paged",
                                             params, tokens, cache,
                                             tables, lengths)
        key, sub = jax.random.split(key)
        nxt = self._sample_from_logits(logits, sub, temps, top_ps)
        return nxt, key, cache, _moe.fold_expert_stats(b)

    def _prefill_chunk_fn(self, params, cache, tokens, lengths, advance):
        # chunked prefill (ISSUE 20): one fixed-shape [B, chunk] window
        # over ALL batch slots — rows with advance=0 write masked
        # garbage above their valid length, exactly the spec-verify
        # convention.  `lengths` is the HOST scheduler mirror, so the
        # executable rewrites every row's in-graph length from it
        # (retired slots can't leave stale lengths behind).
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(
                self.model, "prefill_chunk", params, tokens, cache,
                lengths, advance)
        return logits, cache, _moe.fold_expert_stats(b)

    def _prefill_chunk_paged_fn(self, params, cache, tokens, tables,
                                lengths, advance):
        with _moe.collect_expert_stats() as b:
            logits, cache = functional_apply(
                self.model, "prefill_chunk_paged", params, tokens,
                cache, tables, lengths, advance)
        return logits, cache, _moe.fold_expert_stats(b)

    # ---- timing helpers -----------------------------------------------
    # executable-observatory kind per _timed key family (ISSUE 15): the
    # registry groups rooflines by these
    _EXEC_KIND = {"prefill": "prefill", "prefill_paged": "prefill",
                  "prefill_paged_ext": "prefill", "disagg": "prefill",
                  "disagg_ext": "prefill", "draft_prefill": "prefill",
                  "prefill_chunk": "prefill",
                  "prefill_chunk_paged": "prefill",
                  "decode": "decode", "spec_tick": "spec_verify",
                  "sample": "sample", "handoff_gather": "handoff",
                  "handoff_scatter": "handoff"}

    def _register_exec(self, key, jitfn, args, mesh=None):
        """Join the process exec registry at compile time (the first
        call of this key): shape structs are captured BEFORE the call
        runs, so donation never invalidates what analyze() re-lowers
        from.  Registration is dict writes only — the XLA cost/memory
        analysis stays deferred until something asks for it."""
        fam = key[0] if isinstance(key, tuple) else str(key)
        kind = self._EXEC_KIND.get(fam, str(fam))
        meta = {"kv_layout": self.kv_layout,
                "kv_dtype": self.kv_dtype or "dense"}
        # pod-scale serving (ISSUE 18): the entry records WHICH devices
        # it compiled against and the tp degree, so the observatory can
        # tell a tp-sharded decode from a single-chip one (and the
        # disagg prefill submesh from the decode submesh)
        tp = 1
        if mesh is not None:
            tp = int(dict(mesh.shape).get("tp", 1))
            meta["tp"] = tp
            # expert parallelism (ISSUE 19): the submesh shape below
            # already carries every axis — recording ep explicitly lets
            # the observatory (and comm_stats' per-axis collective
            # fold) tell an expert-parallel decode apart at a glance
            meta["ep"] = int(dict(mesh.shape).get("ep", 1))
            meta["submesh"] = {
                "shape": {ax: int(n) for ax, n in mesh.shape.items()},
                "devices": [int(d.id) for d in
                            np.asarray(mesh.devices).flat]}
        if kind == "decode":
            from ..ops.decode_megakernel import megakernel_enabled
            # the megakernel stands down under tp>1 (gpt._megakernel
            # _active) — the registry must say what actually compiled
            if megakernel_enabled(self.model.cfg) and tp == 1:
                kind = "megakernel_decode"
                meta["megakernel"] = True
            meta["batch_slots"] = self.batch_slots
        elif kind == "spec_verify":
            meta["spec_k"] = self.spec_k
        if isinstance(key, tuple) and len(key) > 1 and key[1]:
            meta["bucket"] = int(key[1])
        # donation per family, matching the jax.jit construction: the
        # sampler never donates, the spec tick donates both caches
        # (spec_decode.py argnums 2+3), everything else donates its
        # cache operand 1 — the registry's donation evidence must be
        # what the executable actually does
        if not self._donate or kind == "sample":
            donate = ()
        elif kind == "spec_verify":
            donate = (2, 3)
        else:
            donate = (1,)
        _exec_registry.register(
            self._exec_component, key, kind, jitfn=jitfn, args=args,
            donate_argnums=donate, meta=meta)

    _MESH_DEFAULT = object()   # sentinel: "use self.mesh"

    def _timed_exec(self, kind, key, jitfn, *args, mesh=_MESH_DEFAULT):
        """_timed with observatory wiring: the jitted callable and its
        args are visible here, so the first call registers the
        executable and steady-state calls pair their wall time with the
        registry entry (one dict lookup + two adds — zero syncs).
        ``mesh`` overrides the compile mesh for this key (the disagg
        PrefillWorker traces against its OWN submesh); the default is
        the engine's serving mesh."""
        if mesh is self._MESH_DEFAULT:
            mesh = self.mesh
        if key not in self._first_call_keys and _exec_registry.enabled():
            self._register_exec(key, jitfn, args, mesh=mesh)
        return self._timed(kind, key, lambda: jitfn(*args), mesh=mesh)

    def _timed(self, kind, key, fn, mesh=_MESH_DEFAULT):
        if mesh is self._MESH_DEFAULT:
            mesh = self.mesh
        if mesh is not None and key not in self._first_call_keys:
            # first call per key = the trace: publish the mesh on BOTH
            # channels (ambient + compile) so trace-time decisions —
            # _megakernel_active's tp gate, the decode kernels'
            # shard_map wrapper — see the serving mesh.  Steady-state
            # calls skip the guard entirely (zero per-tick overhead).
            from ..distributed.mesh import compile_mesh_guard
            with compile_mesh_guard(mesh):
                return self._timed_inner(kind, key, fn)
        return self._timed_inner(kind, key, fn)

    # first-call traces are serialized PROCESS-WIDE: two replicas of
    # the same model driven from different threads (the RPC fleet
    # loadtest, a multi-replica router) would otherwise trace jax
    # programs concurrently over the SHARED module tree and leak
    # tracers into each other's traces.  Steady-state calls never take
    # the lock — only the one cold call per executable key does.
    _trace_lock = threading.RLock()

    def _timed_inner(self, kind, key, fn):
        t0 = time.perf_counter()
        if key not in self._first_call_keys:
            # first call per executable = trace + compile/deserialize
            self._first_call_keys.add(key)
            with self._trace_lock:
                if self._suspend_cache_hits:
                    with compile_cache.suspend_cpu_cache_hits():
                        out = fn()
                else:
                    out = fn()
            dt = (time.perf_counter() - t0) * 1e3
            self._timings["compile_ms_cold"] += dt
            _exec_registry.registry().note_compile(
                self._exec_component, key, dt)
        else:
            out = fn()
            dt = (time.perf_counter() - t0) * 1e3
            self._timings[kind] += dt
            _exec_registry.note_runtime(self._exec_component, key, dt)
        return out

    # ---- public API ---------------------------------------------------
    def add_request(self, prompt, max_new_tokens: int = 32,
                    eos_id: Optional[int] = None,
                    temperature: float = 0.0, top_p: float = 1.0,
                    deadline_s: Optional[float] = None) -> int:
        """Queue a generation request; returns its id. Admitted into a
        free slot (dense) / free blocks (paged) at the next step().
        deadline_s (seconds from NOW, queueing included): past it the
        request is retired with whatever it generated and reported
        timed_out, instead of holding a decode slot forever."""
        req = Request(prompt, max_new_tokens, eos_id, temperature, top_p,
                      deadline_s=deadline_s)
        if req.prompt.size > self.buckets[-1]:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens exceeds the largest "
                f"prefill bucket ({self.buckets[-1]})")
        if req.prompt.size >= self.max_seq_len:
            raise ValueError(
                f"prompt of {req.prompt.size} tokens leaves no room to "
                f"generate within max_seq_len={self.max_seq_len}")
        if self.kv_layout == "paged":
            # can this request EVER run alone on an empty pool?  (its
            # transient bucket-padded prefill, then its steady state)
            bs = self.block_size
            worst = max(
                blocks_for(self._bucket_for(req.prompt.size), bs),
                # spec ticks write a K+1 window before the scheduler
                # knows how much of it commits, so the steady-state
                # extent carries that margin
                blocks_for(min(req.prompt.size + req.max_new_tokens
                               + self.spec_k, self.max_seq_len), bs))
            if worst > self._alloc.capacity:
                raise ValueError(
                    f"request needs {worst} KV blocks but the pool only "
                    f"has {self._alloc.capacity} — raise "
                    f"PADDLE_TPU_KV_BLOCKS or shrink the request")
        self._queue.append(req)
        return req.rid

    def generate(self, prompt, max_new_tokens: int = 32,
                 eos_id: Optional[int] = None, temperature: float = 0.0,
                 top_p: float = 1.0,
                 deadline_s: Optional[float] = None) -> np.ndarray:
        """Blocking single-request generation THROUGH the admission
        queue: on a busy/full engine this waits for capacity (driving
        step() retires slots and frees blocks) instead of raising.
        In-flight requests keep decoding while it waits.  With
        deadline_s the wait is bounded: past the deadline the partial
        generation (possibly empty) is returned."""
        rid = self.add_request(prompt, max_new_tokens=max_new_tokens,
                               eos_id=eos_id, temperature=temperature,
                               top_p=top_p, deadline_s=deadline_s)
        while rid not in self.results:
            if self._guard is not None and self._guard.preempted:
                # server preempted while we were queued: drain and hand
                # back whatever exists (empty if never admitted)
                self.undelivered.extend(self.drain(self._guard_timeout))
                return self.results.get(rid, np.zeros(0, np.int32))
            self.step_or_raise()
        return self.results[rid]

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    # ---- paged block accounting ---------------------------------------
    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        """Allocate n blocks, evicting unpinned radix-cache blocks if
        the free list alone cannot cover it."""
        if n <= 0:
            return []
        out = self._alloc.alloc(n)
        if out is None and self._prefix is not None:
            self._prefix.evict(n - self._alloc.num_free)
            out = self._alloc.alloc(n)
        return out

    def _free_slot_blocks(self, slot: int):
        self._alloc.decref(self._slot_blocks[slot])
        self._slot_blocks[slot] = []
        self._tables[slot, :] = 0
        self._slot_len[slot] = 0

    def _release_slot(self, req: Request):
        """Shared slot teardown for retirement AND preemption — every
        per-slot sampling field is reset in exactly one place."""
        slot = req.slot
        if self.kv_layout == "paged":
            self._free_slot_blocks(slot)
        self._slots[slot] = None
        self._temps[slot] = 0.0
        self._top_ps[slot] = 1.0
        if self._spec is not None:
            self._spec.on_release(slot)
        req.slot = None
        req.prefilling = False
        req.prefill_pos = 0
        # any release can make blocks free OR evictable — wake the
        # head-of-line admission memo (see _hol_block)
        self._release_epoch += 1

    def _preempt(self, req: Request):
        """Kick an active request back onto the queue head: free its
        blocks now, resume later via a prefill over prompt+generated
        (which usually hits the radix cache for the original prompt).
        The sampled-but-unwritten last token is re-derived by that
        prefill, so no state is lost."""
        req.resume_prompt = np.concatenate(
            [req.prompt, np.asarray(req.generated, np.int32)])
        req.preemptions += 1
        now = time.perf_counter()
        # a still-PREFILLING victim (chunked mode) never went live:
        # it has no decode activation to account or close
        if req.t_live is not None:
            req.active_s += now - req.t_live
        req.t_queue_since = now
        self._timings["preemptions"] += 1
        self._m_preempts.inc()
        if self._tracer.active:
            tr = self._tracer
            if req.t_live is not None:
                t_live = tr.to_us(req.t_live)
                tr.complete("decode", t_live, tr.to_us(now) - t_live,
                            pid=_spans.PID_REQUESTS, tid=req.rid,
                            cat="request",
                            args={"tokens": len(req.generated),
                                  "preempted": True})
            else:
                t_adm = tr.to_us(req.t_admit)
                tr.complete("prefill", t_adm, tr.to_us(now) - t_adm,
                            pid=_spans.PID_REQUESTS, tid=req.rid,
                            cat="request",
                            args={"chunk_pos": req.prefill_pos,
                                  "preempted": True})
            tr.instant("preempt", pid=_spans.PID_REQUESTS, tid=req.rid,
                       cat="request", ts_us=tr.to_us(now))
        req.t_live = None
        self._release_slot(req)
        self._queue.appendleft(req)

    def _preempt_for_blocks(self, n: int,
                            exclude: Request) -> Optional[List[int]]:
        """Pool is dry mid-decode: preempt the YOUNGEST other active
        request(s) until n blocks come free (vLLM's recompute-style
        preemption).  Only victims whose resume prefill fits a bucket
        qualify — with default buckets that is everyone."""
        while True:
            out = self._alloc_blocks(n)
            if out is not None:
                return out
            # a victim must be RESUMABLE: its prompt+generated fits a
            # prefill bucket AND that bucket's cold admission fits the
            # pool (else it could never re-admit and the queue stalls)
            victims = [
                r for r in self._slots
                if r is not None and r is not exclude
                and len(r.prompt) + len(r.generated) <= self.buckets[-1]
                and blocks_for(
                    self._bucket_for(len(r.prompt) + len(r.generated)),
                    self.block_size) <= self._alloc.capacity]
            if not victims:
                return None
            self._preempt(max(victims, key=lambda r: r.admit_seq))

    # ---- admission ----------------------------------------------------
    def _try_admit(self, req: Request, slot: int) -> bool:
        """Admit into `slot` if capacity allows; False leaves the
        request at the queue head (head-of-line order is FIFO)."""
        if self.kv_layout == "dense":
            self._admit_dense(req, slot)
            return True
        return self._admit_paged(req, slot)

    def _record_admission(self, req: Request, slot: int, plen: int,
                          logits):
        """Shared tail of both admission paths: sample the first token
        from the prefill logits, bind the request to its slot."""
        self._key, sub = jax.random.split(self._key)
        # np (not list) literals: a python-float list would lower an
        # extra convert_element_type executable on the admission path
        tok = self._timed_exec(
            "prefill_ms", ("sample", 1), self._sample_jit,
            logits, sub,
            np.asarray([req.temperature], np.float32),
            np.asarray([req.top_p], np.float32))
        tok = int(np.asarray(tok)[0])
        async_dispatch.record_host_sync()
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
            self._m_ttft.observe((now - req.t_enqueue) * 1e3)
        req.t_live = now
        req.token_times.append(now)
        req.queued_s += req.t_admit - req.t_queue_since
        self._timings["prefills"] += 1
        self._m_prefills.inc()
        if self._tracer.active:
            # request-lifecycle timeline: close the queued span, record
            # the prefill span (host timestamps already on hand — no
            # extra clock reads beyond `now` above)
            tr = self._tracer
            t_q = tr.to_us(req.t_queue_since)
            t_adm = tr.to_us(req.t_admit)
            tr.complete("queued", t_q, t_adm - t_q,
                        pid=_spans.PID_REQUESTS, tid=req.rid,
                        cat="request",
                        args={"prompt_tokens": int(req.prompt.size),
                              "resume": req.resume_prompt is not None})
            tr.complete("prefill", t_adm, tr.to_us(now) - t_adm,
                        pid=_spans.PID_REQUESTS, tid=req.rid,
                        cat="request", args={"slot": slot})
        req.slot = slot
        req.admit_seq = next(self._admit_counter)
        self._slots[slot] = req
        self._slot_len[slot] = plen
        self._temps[slot] = req.temperature
        self._top_ps[slot] = req.top_p
        req.generated.append(tok)
        self._next_token[slot] = tok
        self._retire_if_done(req, tok)
        if self._spec is not None and self._slots[slot] is req:
            # the draft prefills the same (full) prompt and the first
            # sampled token seeds its catch-up window
            self._spec.on_admit(req, slot, tok)

    def _admit_dense(self, req: Request, slot: int):
        prompt = req.effective_prompt()
        bucket = self._bucket_for(prompt.size)
        ids = np.zeros((1, bucket), np.int32)
        ids[0, :prompt.size] = prompt
        plen = prompt.size
        req.t_admit = time.perf_counter()
        self._timings["prefill_tokens"] += bucket
        logits, cache, moe = self._timed_exec(
            "prefill_ms", ("prefill", bucket), self._prefill_jit,
            self.params, self.cache, jnp.asarray(ids),
            np.int32(slot), np.int32(plen))
        self.cache = cache
        self._accum_moe(moe)
        self._record_admission(req, slot, plen, logits)

    def _admit_paged(self, req: Request, slot: int) -> bool:
        """Paged admission: one in-engine prefill, then the same slot
        adoption a disaggregated handoff uses."""
        rec = self._paged_prefill(req, self._prefill_paged_cold_jit,
                                  self._prefill_paged_ext_jit,
                                  "prefill_paged")
        if rec is None:
            return False                      # stay queued; retry later
        blocks, _plen, logits = rec
        self.admit_handoff(req, slot, blocks, logits)
        return True

    def _paged_prefill(self, req: Request, cold_jit, ext_jit,
                       key_prefix: str, domain=None):
        """The paged prefill body: match the radix cache, allocate
        blocks for the divergent suffix's bucket, prefill ONLY the
        suffix, then trim the bucket-padding blocks and adopt the
        prompt into the radix tree.  Returns ``(blocks, plen, logits)``
        with the slot-lifetime refcounts TAKEN (the caller installs the
        block table and finishes admission), or None when the pool
        cannot hold the request yet.  Parameterized over the compiled
        executables AND the state ``domain`` (params / cache / block
        allocator / radix cache / mesh) so the in-engine admission path
        and the disaggregated PrefillWorker — which under disjoint
        disaggregation owns a SEPARATE pool on its own device group —
        share one implementation.  ``domain=None`` means self."""
        dom = domain if domain is not None else self
        bs = self.block_size
        prompt = req.effective_prompt()
        pc_stats0 = None
        if dom._prefix is not None:
            # a blocked head-of-line request re-matches on every retry;
            # roll the hit counters back on failure so the reported hit
            # rate counts admissions, not retries
            pc_stats0 = (dom._prefix.queries, dom._prefix.hit_queries,
                         dom._prefix.hit_blocks)
            shared, prefix_len = dom._prefix.match(prompt)
        else:
            shared, prefix_len = [], 0
        # the bucket-padded extent must fit BOTH the slot's block table
        # (coarse bucket sets can push prefix+bucket past max_seq) AND
        # the whole pool (a large prefix hit on a shrunk pool can
        # demand more blocks than exist — and the matched blocks are
        # pinned by our own incref, so eviction could never save it):
        # shed cached prefix blocks (recompute those tokens) until it
        # does — prefix_len=0 always fits, because add_request already
        # guaranteed blocks_for(bucket_for(prompt)) <= capacity
        fit = min(self.blocks_per_slot, dom._alloc.capacity)
        shed = 0
        while shared and blocks_for(
                prefix_len + self._bucket_for(prompt.size - prefix_len),
                bs) > fit:
            shared = shared[:-1]
            prefix_len -= bs
            shed += 1
        if shed and pc_stats0 is not None:
            # shed blocks were never reused — keep the hit counters
            # honest (a fully-shed match is not a hit at all)
            dom._prefix.hit_blocks -= shed
            if not shared:
                dom._prefix.hit_queries -= 1
        suffix = prompt[prefix_len:]
        bucket = self._bucket_for(suffix.size)
        need_total = blocks_for(prefix_len + bucket, bs)
        # the slot's OWN reference on the shared prefix blocks, taken
        # BEFORE any allocation: _alloc_blocks may evict radix leaves,
        # and a matched block whose only reference is the tree's
        # (refcount 1) would otherwise be freed and re-handed out as
        # this same request's "fresh" suffix block — aliasing the block
        # table and corrupting the shared prefix KV
        dom._alloc.incref(shared)
        new_blocks = dom._alloc_blocks(need_total - len(shared))
        if new_blocks is None:
            dom._alloc.decref(shared)
            if pc_stats0 is not None:
                (dom._prefix.queries, dom._prefix.hit_queries,
                 dom._prefix.hit_blocks) = pc_stats0
            return None                       # stay queued; retry later
        blocks = list(shared) + new_blocks
        req.t_admit = time.perf_counter()
        # the prefix-cache win in one number: a hit admission prefills
        # only the divergent suffix's bucket, not the whole prompt's
        self._timings["prefill_tokens"] += bucket

        ids = np.zeros((1, bucket), np.int32)
        ids[0, :suffix.size] = suffix
        row = np.zeros(self.blocks_per_slot, np.int32)
        row[:len(blocks)] = blocks
        if prefix_len == 0:
            logits, cache, moe = self._timed_exec(
                "prefill_ms", (key_prefix, bucket), cold_jit,
                dom.params, dom.cache, jnp.asarray(ids),
                jnp.asarray(row), np.int32(suffix.size),
                mesh=dom.mesh)
        else:
            logits, cache, moe = self._timed_exec(
                "prefill_ms", (key_prefix + "_ext", bucket), ext_jit,
                dom.params, dom.cache, jnp.asarray(ids),
                jnp.asarray(row), np.int32(prefix_len),
                np.int32(suffix.size), mesh=dom.mesh)
        dom.cache = cache
        self._accum_moe(moe)

        # trim: blocks past the REAL prompt extent only ever held bucket
        # padding — return them to the pool immediately
        plen = int(prefix_len + suffix.size)          # == prompt.size
        keep = blocks_for(plen, bs)
        if len(blocks) > keep:
            dom._alloc.decref(blocks[keep:])
            blocks = blocks[:keep]
        # adopt the prompt's full blocks into the radix tree so the NEXT
        # request sharing this prefix skips its prefill
        if dom._prefix is not None:
            n_full = prompt.size // bs
            if n_full:
                dom._prefix.insert(prompt[:n_full * bs],
                                   blocks[:n_full])
        return blocks, plen, logits

    def admit_handoff(self, req: Request, slot: int, blocks, logits):
        """Adopt a request whose prefill ALREADY ran elsewhere (the
        disaggregated prefill worker — inference.disagg): install its
        block table and finish admission from the handed-off last-token
        logits.  The blocks arrive trimmed, radix-adopted and owned by
        this slot (the worker took the slot's refcounts); no prefill
        executable runs on the decode side — that is the point."""
        if self.kv_layout != "paged":
            raise ValueError("admit_handoff needs the paged layout — "
                             "the KV handoff travels through the block "
                             "pool")
        plen = int(req.effective_prompt().size)
        self._slot_blocks[slot] = list(blocks)
        self._tables[slot, :] = 0
        self._tables[slot, :len(blocks)] = blocks
        self._record_admission(req, slot, plen, logits)

    # ---- chunked prefill (ISSUE 20) -----------------------------------
    def _try_admit_chunked(self, req: Request, slot: int) -> bool:
        """Chunked admission: bind the request to a slot and let
        _chunk_tick feed its prompt through the chunk executable a
        budget at a time — NO prefill executable runs here, so the
        decode batch never stalls behind it.  Paged, the slot starts
        with blocks covering its radix-matched prefix plus the first
        chunk; False (pool dry) leaves it at the queue head."""
        prompt = req.effective_prompt()
        plen0 = 0
        if self.kv_layout == "paged":
            bs = self.block_size
            pc_stats0 = None
            if self._prefix is not None:
                pc_stats0 = (self._prefix.queries,
                             self._prefix.hit_queries,
                             self._prefix.hit_blocks)
                shared, prefix_len = self._prefix.match(prompt)
            else:
                shared, prefix_len = [], 0
            # the match can't exceed the slot's table (coarse pools):
            # shed cached blocks until it fits, same as _paged_prefill
            fit = min(self.blocks_per_slot, self._alloc.capacity)
            shed = 0
            while shared and len(shared) > fit:
                shared = shared[:-1]
                prefix_len -= bs
                shed += 1
            if shed and pc_stats0 is not None:
                self._prefix.hit_blocks -= shed
                if not shared:
                    self._prefix.hit_queries -= 1
            first = min(self.prefill_chunk, prompt.size - prefix_len)
            need = blocks_for(prefix_len + first, bs)
            # slot's own reference on the shared prefix BEFORE any
            # allocation (the aliasing hazard _paged_prefill documents)
            self._alloc.incref(shared)
            new_blocks = self._alloc_blocks(need - len(shared))
            if new_blocks is None:
                self._alloc.decref(shared)
                if pc_stats0 is not None:
                    (self._prefix.queries, self._prefix.hit_queries,
                     self._prefix.hit_blocks) = pc_stats0
                return False                  # stay queued; retry later
            blocks = list(shared) + new_blocks
            self._slot_blocks[slot] = blocks
            self._tables[slot, :] = 0
            self._tables[slot, :len(blocks)] = blocks
            plen0 = prefix_len
        now = time.perf_counter()
        req.t_admit = now
        req.queued_s += now - req.t_queue_since
        req.prefilling = True
        req.prefill_pos = plen0
        req.slot = slot
        req.admit_seq = next(self._admit_counter)
        self._slots[slot] = req
        self._slot_len[slot] = plen0
        self._temps[slot] = req.temperature
        self._top_ps[slot] = req.top_p
        if self._tracer.active:
            tr = self._tracer
            t_q = tr.to_us(req.t_queue_since)
            tr.complete("queued", t_q, tr.to_us(now) - t_q,
                        pid=_spans.PID_REQUESTS, tid=req.rid,
                        cat="request",
                        args={"prompt_tokens": int(req.prompt.size),
                              "resume": req.resume_prompt is not None})
        return True

    def _ensure_chunk_room(self, req: Request, adv: int) -> int:
        """Grow ``req``'s block extent to cover its next ``adv`` chunk
        tokens (free list → radix eviction → preempt-youngest) —
        _ensure_decode_room made chunk-granular.  Returns the advance
        that is actually safe: 0 when the requester itself had to be
        preempted (a still-prefilling requester is ALWAYS resumable —
        its prompt fits a bucket by add_request and generated is
        empty — so the degrade path preempts, never retires)."""
        slot = req.slot
        while (self._slots[slot] is req and blocks_to_extend(
                len(self._slot_blocks[slot]),
                req.prefill_pos + adv, self.block_size) > 0):
            nb = self._alloc_blocks(1)
            if nb is None:
                nb = self._preempt_for_blocks(1, exclude=req)
            if nb is None:
                self._preempt(req)
                break
            idx = len(self._slot_blocks[slot])
            self._slot_blocks[slot].append(nb[0])
            self._tables[slot, idx] = nb[0]
        return adv if self._slots[slot] is req else 0

    def _chunk_tick(self) -> int:
        """Advance every still-prefilling slot by up to
        ``prefill_chunk`` prompt tokens TOTAL (oldest admission first
        — FIFO inside the tick too) through ONE fixed-shape chunk
        executable, then graduate slots whose prompt completed.
        Returns the number of first tokens sampled (graduations) —
        the same thing monolithic admission counts as produced."""
        pre = [(s, r) for s, r in enumerate(self._slots)
               if r is not None and r.prefilling]
        if not pre:
            return 0
        pre.sort(key=lambda sr: sr[1].admit_seq)
        c = self.prefill_chunk
        budget = c
        tokens = np.zeros((self.batch_slots, c), np.int32)
        advance = np.zeros(self.batch_slots, np.int32)
        tick_wall0 = time.perf_counter()
        for slot, req in pre:
            if budget <= 0:
                break
            prompt = req.effective_prompt()
            adv = min(prompt.size - req.prefill_pos, budget)
            if self.kv_layout == "paged":
                # may preempt OTHER prefilling slots (their batch rows
                # become no-ops: the exec reads tables/lengths at call
                # time, and a freed slot's zeroed table row routes its
                # writes into the null block)
                adv = self._ensure_chunk_room(req, adv)
            if self._slots[slot] is not req or adv <= 0:
                continue
            tokens[slot, :adv] = prompt[req.prefill_pos:
                                        req.prefill_pos + adv]
            advance[slot] = adv
            budget -= adv
        if not advance.any():
            return 0
        self._timings["prefill_tokens"] += int(advance.sum())
        if self.kv_layout == "paged":
            logits, cache, moe = self._timed_exec(
                "prefill_ms", ("prefill_chunk_paged", c),
                self._prefill_chunk_paged_jit,
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(self._tables),
                jnp.asarray(self._slot_len.astype(np.int32)),
                jnp.asarray(advance))
        else:
            logits, cache, moe = self._timed_exec(
                "prefill_ms", ("prefill_chunk", c),
                self._prefill_chunk_jit,
                self.params, self.cache, jnp.asarray(tokens),
                jnp.asarray(self._slot_len.astype(np.int32)),
                jnp.asarray(advance))
        self.cache = cache
        if moe is not None:
            # park the fold: np.asarray'ing it here would cost a host
            # sync per chunk tick — it drains at the next real sync
            self._moe_pending.append(moe)
        grads = []
        for slot, req in pre:
            if self._slots[slot] is not req:
                continue
            adv = int(advance[slot])
            if adv <= 0:
                continue
            req.prefill_pos += adv
            self._slot_len[slot] = req.prefill_pos
            prompt = req.effective_prompt()
            if self.kv_layout == "paged" and self._prefix is not None:
                # progressive adoption: completed blocks join the radix
                # tree NOW, so a same-prefix request admitted while
                # this one is mid-prefill already shares them (insert
                # is idempotent — existing nodes win)
                n_full = req.prefill_pos // self.block_size
                if n_full:
                    self._prefix.insert(
                        prompt[:n_full * self.block_size],
                        self._slot_blocks[slot][:n_full])
            if req.prefill_pos >= prompt.size:
                grads.append((slot, req))
        produced = 0
        if grads:
            # batch-wide sampling at a FIXED (sample, batch_slots) key:
            # slicing per graduating slot would compile per slot index
            self._key, sub = jax.random.split(self._key)
            tok = self._timed_exec(
                "prefill_ms", ("sample", self.batch_slots),
                self._sample_jit, logits, sub,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps))
            t0 = time.perf_counter()
            tok_np = np.asarray(tok)
            self._flush_moe()
            async_dispatch.record_host_sync()
            self._timings["sync_ms"] += \
                (time.perf_counter() - t0) * 1e3
            for slot, req in grads:
                self._graduate(req, slot, int(tok_np[slot]))
                produced += 1
        _flightrec.record(
            "chunk_tick",
            dur_ms=(time.perf_counter() - tick_wall0) * 1e3,
            prefilling=len(pre), tokens=int(advance.sum()),
            graduated=produced)
        return produced

    def _graduate(self, req: Request, slot: int, tok: int):
        """A slot's prompt completed its last chunk: commit the first
        sampled token and flip the slot into the decode active set.
        Mirrors _record_admission's tail — when chunked, the request's
        first token and lifecycle spans come from here."""
        now = time.perf_counter()
        if req.t_first is None:
            req.t_first = now
            self._m_ttft.observe((now - req.t_enqueue) * 1e3)
        req.t_live = now
        req.prefilling = False
        req.token_times.append(now)
        self._timings["prefills"] += 1
        self._m_prefills.inc()
        if self._tracer.active:
            tr = self._tracer
            t_adm = tr.to_us(req.t_admit)
            tr.complete("prefill", t_adm, tr.to_us(now) - t_adm,
                        pid=_spans.PID_REQUESTS, tid=req.rid,
                        cat="request",
                        args={"slot": slot, "chunked": True})
        req.generated.append(tok)
        self._next_token[slot] = tok
        self._retire_if_done(req, tok)
        if self._spec is not None and self._slots[slot] is req:
            # the draft catches up over the full prompt now — its
            # (small-model) bucketed prefill runs once per request,
            # exactly as in monolithic admission
            self._spec.on_admit(req, slot, tok)

    def _flush_moe(self):
        """Fold the chunk-tick expert stats parked since the last real
        host sync (see _chunk_tick) — called wherever the scheduler
        already blocks on device results, so it adds zero syncs."""
        for moe in self._moe_pending:
            self._accum_moe(moe)
        self._moe_pending.clear()

    def _ensure_decode_room(self, need_tokens: int = 1):
        """Before a decode step every active slot whose next
        ``need_tokens`` writes would fall past its block extent gets
        fresh blocks — by free list, then radix-cache eviction, then
        preemption of the youngest other request.  This is the
        no-deadlock path ISSUE'd as preempt-to-queue: the dense engine
        could never run out mid-request, the paged one can.
        ``need_tokens`` > 1 is the spec-decode tick, which scatters a
        K+1-token window before knowing how much of it commits."""
        for slot in range(self.batch_slots):
            req = self._slots[slot]
            # still-prefilling slots don't decode — their room is
            # chunk-granular (_ensure_chunk_room); the decode exec's
            # write on their row lands in masked garbage / null block
            if req is None or req.prefilling:
                continue
            need_blocks = blocks_for(
                int(self._slot_len[slot]) + need_tokens, self.block_size)
            while (self._slots[slot] is req
                   and len(self._slot_blocks[slot]) < need_blocks):
                nb = self._alloc_blocks(1)
                if nb is None:
                    nb = self._preempt_for_blocks(1, exclude=req)
                if nb is None:
                    # every OTHER active request has outgrown the
                    # largest bucket (un-resumable victims — possible
                    # with custom coarse bucket lists): degrade the
                    # requester, never the engine.  Preempt it if it
                    # can itself resume; otherwise retire it with the
                    # tokens it has (a memory-capped finish beats
                    # killing every request).
                    total = len(req.prompt) + len(req.generated)
                    if (total <= self.buckets[-1] and blocks_for(
                            self._bucket_for(total), self.block_size)
                            <= self._alloc.capacity):
                        self._preempt(req)
                    else:
                        self._timings["memory_capped_retirements"] += 1
                        self._retire(req)
                    break
                idx = len(self._slot_blocks[slot])
                self._slot_blocks[slot].append(nb[0])
                self._tables[slot, idx] = nb[0]

    def _retire_if_done(self, req: Request, last_tok: int):
        """EOS / max-new-tokens / capacity retirement; frees the slot
        (and, paged, its blocks — minus any the radix cache pins)."""
        full = self._slot_len[req.slot] + 1 >= self.max_seq_len
        if (last_tok == req.eos_id
                or len(req.generated) >= req.max_new_tokens or full):
            self._retire(req)

    def _deliver(self, req: Request):
        """The one place results/request_stats are written — every
        finished request (normal, deadline-expired, drain-forced) goes
        through the same bounded-history caps: a long-running server
        must not grow state per request forever.  results is the
        DELIVERY channel — a step()-driven server is expected to pop
        what it consumes (loadgen does) — so its safety cap is generous
        enough that no realistic single run() batch ever hits it."""
        self.results[req.rid] = np.asarray(req.generated, np.int32)
        self.request_stats[req.rid] = self._request_record(req)
        (self._m_req_to if req.timed_out else self._m_req_ok).inc()
        while len(self.request_stats) > self._request_stats_cap:
            self.request_stats.pop(next(iter(self.request_stats)))
        while len(self.results) > self._results_cap:
            self.results.pop(next(iter(self.results)))

    def _retire(self, req: Request):
        req.done = True
        req.t_finish = time.perf_counter()
        # a deadline/drain retirement can hit a still-prefilling slot
        # (chunked mode) that never went live — nothing to account
        if req.t_live is not None:
            req.active_s += req.t_finish - req.t_live
        if self._tracer.active and req.t_live is not None:
            # close the request track: the decode span of this (final)
            # activation — together with queued/prefill/earlier decode
            # spans this is the full lifecycle timeline
            tr = self._tracer
            t_live = tr.to_us(req.t_live)
            tr.complete("decode", t_live,
                        tr.to_us(req.t_finish) - t_live,
                        pid=_spans.PID_REQUESTS, tid=req.rid,
                        cat="request",
                        args={"tokens": len(req.generated),
                              "preemptions": req.preemptions,
                              "timed_out": req.timed_out})
        self._deliver(req)
        self._release_slot(req)

    def _request_record(self, req: Request) -> dict:
        n = len(req.generated)
        # inter-token latency: gaps between delivery timestamps (first
        # token included) — the per-request tail the load harness pools
        # and coordinated-omission-corrects, same contract as TTFT
        gaps = (np.diff(np.asarray(req.token_times)) * 1e3
                if len(req.token_times) > 1
                else np.zeros(0, np.float64))
        return {
            "prompt_tokens": int(req.prompt.size),
            "tokens": n,
            # a queue-expired request never produced a token: no TTFT
            "ttft_ms": round((req.t_first - req.t_enqueue) * 1e3, 3)
            if req.t_first is not None else None,
            "queued_ms": round(req.queued_s * 1e3, 3),
            # over ACTIVE decode time only — requeue waits excluded
            "decode_tokens_per_sec": round((n - 1) / req.active_s, 2)
            if n > 1 and req.active_s > 0 else None,
            "itl_ms_p50": round(float(np.percentile(gaps, 50)), 3)
            if gaps.size else None,
            "itl_ms_p99": round(float(np.percentile(gaps, 99)), 3)
            if gaps.size else None,
            # raw gaps (bounded) so the harness can correct the first
            # gap for scheduled-arrival lateness and pool across
            # requests
            "itl_gaps_ms": [round(float(g), 3) for g in gaps[:512]],
            "preemptions": req.preemptions,
            "timed_out": req.timed_out,
        }

    def expire_queued_request(self, req: Request, now: float):
        """Deliver a QUEUED request as deadline-expired (it never took
        a slot, so there is nothing to free) — the one place this
        bookkeeping lives; the engine's own sweep and the disaggregated
        wrapper's queue both route here."""
        req.timed_out = True
        req.done = True
        req.t_finish = now
        req.queued_s += now - req.t_queue_since
        self._timings["deadline_retirements"] += 1
        self._deliver(req)

    def _retire_expired(self):
        """Deadline sweep (per step): queued requests past their
        deadline are delivered empty without ever taking a slot; active
        ones are retired mid-generation — slot and paged blocks freed —
        with the tokens they produced so far."""
        now = time.perf_counter()
        expired = [r for r in self._queue
                   if r.deadline is not None and now >= r.deadline]
        for r in expired:
            self._queue.remove(r)
            self.expire_queued_request(r, now)
        for req in list(self._slots):
            if req is not None and req.deadline is not None \
                    and now >= req.deadline:
                req.timed_out = True
                self._timings["deadline_retirements"] += 1
                self._retire(req)

    @property
    def num_active(self) -> int:
        return sum(1 for r in self._slots if r is not None)

    @property
    def blocks_in_use(self) -> Optional[int]:
        return self._alloc.num_in_use if self._alloc else None

    @property
    def _admitting(self) -> bool:
        """Admission gate: closed while draining (engine.drain or a
        fired PreemptionGuard) — in-flight slots finish, the queue
        waits/returns."""
        return not self._draining and (
            self._guard is None or not self._guard.preempted)

    def _watchdog_beat(self):
        """Arm the stall watchdog on the first tick when
        PADDLE_TPU_WATCHDOG_S is set, then heartbeat it."""
        if not self._wd_checked:
            self._wd_checked = True
            t = _watchdog.watchdog_seconds()
            if t is not None:
                self.watchdog = _watchdog.Watchdog(
                    t, label=f"decode_{self.telemetry_label}").arm()
        if self.watchdog is not None:
            self.watchdog.beat()

    def _watchdog_idle_if_empty(self):
        """Park the watchdog when the engine leaves this tick with no
        work — a quiet server between arrivals is not a stall."""
        if self.watchdog is not None and not self.has_work:
            self.watchdog.idle()

    def step(self) -> int:
        """Admit queued requests into free slots, then decode one token
        for every active slot. Returns the number of tokens produced
        this step (admission prefills included)."""
        produced = 0
        self._watchdog_beat()
        if self._retuner is not None:
            # runs a PENDING retune episode only on a quiesced replica
            # (no active slots, empty queue); O(1) otherwise
            self._retuner.on_tick()
        tick_wall0 = time.perf_counter()
        if self._profile is not None:
            # PADDLE_TPU_PROFILE=start:stop over DECODE TICKS
            self._profile.on_step(self._timings["decode_steps"])
        self._m_queue.set(len(self._queue))
        self._retire_expired()
        stall_t0 = time.perf_counter()
        had_active = any(r is not None and not r.prefilling
                         for r in self._slots)
        admitted = 0
        for slot in range(self.batch_slots):
            if not self._admitting:
                break
            if self._slots[slot] is not None or not self._queue:
                continue
            head = self._queue[0]
            # head-of-line memo (ISSUE 20 bugfix): the blocked head's
            # failed radix-match/alloc is NOT re-run until blocks came
            # free (num_free grew) or became evictable (release epoch
            # moved) — deadline expiry above still applies to it
            if (self._alloc is not None and self._hol_block is not None
                    and self._hol_block[0] == head.rid
                    and self._alloc.num_free <= self._hol_block[1]
                    and self._release_epoch == self._hol_block[2]):
                break
            # paged admission is by FREE BLOCKS, not just a free slot;
            # head-of-line FIFO: if the head can't fit, nobody jumps it
            ok = (self._try_admit_chunked(head, slot) if self._chunked
                  else self._try_admit(head, slot))
            if not ok:
                if self._alloc is not None:
                    self._hol_block = (head.rid, self._alloc.num_free,
                                       self._release_epoch)
                break
            self._hol_block = None
            self._queue.popleft()
            admitted += 1
            if not self._chunked:
                produced += 1
        if not self._chunked and admitted and had_active:
            # monolithic admission ran its prefill(s) while live decode
            # streams sat frozen — the interference chunking removes
            self._timings["prefill_stall_ms"] += \
                (time.perf_counter() - stall_t0) * 1e3
        if self._chunked:
            # NOT gated on _admitting: a draining engine must finish
            # the prompts already bound to slots
            produced += self._chunk_tick()
        active_np = np.asarray(
            [1 if (r is not None and not r.prefilling) else 0
             for r in self._slots], np.int32)
        if not active_np.any():
            self._watchdog_idle_if_empty()
            return produced
        if self._spec is not None:
            produced += self._step_spec()
            self._watchdog_idle_if_empty()
            return produced
        if self.kv_layout == "paged":
            self._ensure_decode_room()
            # a preemption/memory-capped retirement may have emptied
            # slots; refresh the mask BEFORE accumulating occupancy so
            # the stats describe the decode step that actually runs
            active_np = np.asarray(
                [1 if (r is not None and not r.prefilling) else 0
                 for r in self._slots], np.int32)
            if not active_np.any():
                self._watchdog_idle_if_empty()
                return produced
            self._timings["block_occupancy_sum"] += \
                self._alloc.num_in_use / self._alloc.capacity
        self._timings["occupancy_sum"] += float(active_np.mean())
        n_active = int(active_np.sum())
        self._m_active.set(n_active)
        tick_t0 = self._tracer.now_us() if self._tracer.active else 0.0
        if self.kv_layout == "paged":
            nxt, self._key, cache, moe = self._timed_exec(
                "decode_ms", ("decode", 0), self._decode_paged_jit,
                self.params, self.cache,
                jnp.asarray(self._next_token),
                jnp.asarray(self._tables),
                jnp.asarray(self._slot_len.astype(np.int32)),
                self._key, jnp.asarray(self._temps),
                jnp.asarray(self._top_ps))
        else:
            nxt, self._key, cache, moe = self._timed_exec(
                "decode_ms", ("decode", 0), self._decode_jit,
                self.params, self.cache,
                jnp.asarray(self._next_token),
                jnp.asarray(active_np), self._key,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        self.cache = cache
        # the ONE host sync of the decode step: the scheduler needs the
        # sampled ids for EOS retirement and admission (the expert-load
        # fold, when present, is a sibling output of the same executable
        # — fetching it here rides the same sync)
        t0 = time.perf_counter()
        nxt_np = np.asarray(nxt)
        self._flush_moe()        # parked chunk-tick folds ride this sync
        self._accum_moe(moe)
        async_dispatch.record_host_sync()
        self._timings["sync_ms"] += (time.perf_counter() - t0) * 1e3
        self._timings["decode_steps"] += 1
        self._m_ticks.inc()
        self._m_tokens.inc(n_active)
        if self._tracer.active:
            now_us = self._tracer.now_us()
            self._tracer.complete("decode_tick", tick_t0,
                                  now_us - tick_t0, cat="serve",
                                  args={"active": n_active})
        commit_now = time.perf_counter()
        for slot, req in enumerate(self._slots):
            # prefilling rows were inactive this step: their sampled
            # token and cache write are masked garbage, not a commit
            if req is None or req.prefilling:
                continue
            tok = int(nxt_np[slot])
            self._slot_len[slot] += 1        # the token we just appended
            req.generated.append(tok)
            req.token_times.append(commit_now)
            self._next_token[slot] = tok
            produced += 1
            self._timings["tokens_generated"] += 1
            self._retire_if_done(req, tok)
        # flight-recorder ring (host counters only — zero extra syncs)
        # + deterministic stall injection for the watchdog tests
        _flightrec.record(
            "decode_tick",
            dur_ms=(time.perf_counter() - tick_wall0) * 1e3,
            tick=self._timings["decode_steps"], active=n_active,
            tokens=produced)
        from ..testing import faults as _faults
        _faults.maybe_hang(self._timings["decode_steps"])
        self._watchdog_idle_if_empty()
        return produced

    def _step_spec(self) -> int:
        """One speculative tick for every active slot: draft proposes
        K, target verifies K+1 in one executable, the scheduler commits
        the accepted prefix + bonus token.  Still exactly ONE host sync
        — it just pays for ~K+1 tokens now."""
        k = self._spec.k
        # capacity: a slot without room for the whole K+1 window
        # retires now (the window writes at slot_len..slot_len+K).
        # NB: this is up to K tokens EARLIER than a non-spec engine
        # would stop — the token-identity contract therefore requires
        # prompt + max_new + K <= max_seq (counted below so a
        # mis-sized deployment shows up in stats, not in silence)
        for req in list(self._slots):
            if req is not None and not req.prefilling \
                    and int(self._slot_len[req.slot]) + k + 1 \
                    > self.max_seq_len:
                self._timings["spec_capacity_retirements"] += 1
                self._retire(req)
        if self.kv_layout == "paged":
            self._ensure_decode_room(need_tokens=k + 1)
        # still-prefilling slots (chunked mode) sit the tick out: the
        # verify window's garbage writes on their rows land above their
        # valid length and the next chunk scatters over them first
        active_np = np.asarray(
            [1 if (r is not None and not r.prefilling) else 0
             for r in self._slots], np.int32)
        if not active_np.any():
            return 0
        if self.kv_layout == "paged":
            self._timings["block_occupancy_sum"] += \
                self._alloc.num_in_use / self._alloc.capacity
        self._timings["occupancy_sum"] += float(active_np.mean())
        n_active = int(active_np.sum())
        self._m_active.set(n_active)
        tick_t0 = self._tracer.now_us() if self._tracer.active else 0.0
        out = self._spec.tick(active_np)
        # the ONE host sync of the tick: K+1 target-greedy tokens + the
        # committed count per slot, one int32 readback
        t0 = time.perf_counter()
        out_np = np.asarray(out)
        self._flush_moe()        # parked chunk-tick folds ride this sync
        async_dispatch.record_host_sync()
        self._timings["sync_ms"] += (time.perf_counter() - t0) * 1e3
        self._timings["decode_steps"] += 1
        self._timings["spec_ticks"] += 1
        self._timings["spec_slot_ticks"] += int(active_np.sum())
        produced = 0
        commit_now = time.perf_counter()
        for slot, req in enumerate(list(self._slots)):
            if req is None or req.prefilling:
                continue
            n_emit = int(out_np[slot, k + 1])
            toks = out_np[slot, :k + 1]
            # host mirrors the in-graph length advance (dense) / owns
            # it (paged); EOS/max-new truncation below RETIRES the
            # slot, so the un-truncated advance never leaks into a
            # later tick
            self._slot_len[slot] += n_emit
            emitted = []
            retired = False
            for i in range(n_emit):
                tok = int(toks[i])
                req.generated.append(tok)
                req.token_times.append(commit_now)
                emitted.append(tok)
                produced += 1
                self._timings["tokens_generated"] += 1
                if tok == req.eos_id or \
                        len(req.generated) >= req.max_new_tokens:
                    retired = True
                    self._retire(req)
                    break
            # count what actually reached the stream — an EOS/max-new
            # truncation must not inflate accepted_tokens_per_tick
            self._timings["spec_tokens_committed"] += len(emitted)
            if not retired and emitted:
                self._next_token[slot] = emitted[-1]
                self._spec.after_commit(slot,
                                        np.asarray(emitted, np.int32))
        self._m_ticks.inc()
        self._m_tokens.inc(produced)
        if self._tracer.active:
            # spec accept counts per tick, as the timeline args
            now_us = self._tracer.now_us()
            self._tracer.complete(
                "spec_tick", tick_t0, now_us - tick_t0, cat="serve",
                args={"active": n_active, "committed": produced,
                      "k": k})
        _flightrec.record("spec_tick",
                          tick=self._timings["decode_steps"],
                          active=n_active, committed=produced, k=k)
        from ..testing import faults as _faults
        _faults.maybe_hang(self._timings["decode_steps"])
        return produced

    def step_or_raise(self) -> int:
        """step(), turning a wedged scheduler into an error: zero
        progress with nothing active to retire but a non-empty queue
        can never resolve on its own.  All blocking drivers (run /
        generate / the load harness) share this one stall check."""
        if self._guard is not None and self._guard.preempted \
                and not self._draining:
            # drivers that only know step_or_raise (the load harness)
            # must not busy-spin a preempted engine forever: perform
            # the graceful drain here — in-flight slots finish, the
            # queue parks in undelivered, has_work goes False
            self.undelivered.extend(self.drain(self._guard_timeout))
            return 0
        produced = self.step()
        if produced == 0 and self.num_active == 0 and self._queue \
                and self._admitting:
            raise RuntimeError(
                "admission stalled: queued requests but no free "
                "capacity and nothing active to retire")
        return produced

    @property
    def has_work(self) -> bool:
        return bool(self._queue) or self.num_active > 0

    def run(self) -> Dict[int, np.ndarray]:
        """Drive step() until every queued request finished; returns
        {request_id: generated token ids}.  With a PreemptionGuard
        attached, a SIGTERM mid-run switches to a graceful drain:
        in-flight slots finish, still-queued requests land in
        ``engine.undelivered`` for the operator to hand back."""
        while self.has_work:
            if self._guard is not None and self._guard.preempted:
                self.undelivered.extend(self.drain(self._guard_timeout))
                break
            self.step_or_raise()
        return self.results

    def attach_preemption_guard(self, guard,
                                drain_timeout_s: Optional[float] = None):
        """Hook a resilience.PreemptionGuard: once it fires (SIGTERM/
        SIGINT), run()/generate() stop admitting, finish in-flight
        slots (bounded by drain_timeout_s), and return — the serving
        analogue of the trainer's drain-then-checkpoint."""
        self._guard = guard
        self._guard_timeout = drain_timeout_s
        return self

    def drain(self, timeout_s: Optional[float] = None) -> List[Request]:
        """Graceful shutdown: stop admission, decode until every
        in-flight slot retires (or timeout_s passes — stragglers are
        then force-retired with their partial output and flagged
        timed_out), and return the still-queued Requests so the caller
        can re-enqueue them elsewhere.  Paged pools are verified
        leak-free: with the slots empty and the radix cache flushed,
        every block's refcount must be back on the free list."""
        self._draining = True
        t0 = time.perf_counter()
        try:
            while self.num_active > 0:
                if timeout_s is not None and \
                        time.perf_counter() - t0 > timeout_s:
                    for req in [r for r in self._slots if r is not None]:
                        self._timings["drain_forced_retirements"] += 1
                        req.timed_out = True
                        self._retire(req)
                    break
                self.step()
            leftover = list(self._queue)
            self._queue.clear()
            self.check_leak_free()     # slots empty + queue cleared
            return leftover
        finally:
            self._draining = False

    def prefix_summary(self) -> Optional[dict]:
        """The radix cache's router-facing digest (block-granular
        fingerprint set + hit/evict counters), or None when this engine
        runs without a prefix cache.  Cheap: the fingerprint set is
        maintained incrementally, no tree walk happens here."""
        return self._prefix.summary() if self._prefix is not None else None

    def flush_prefix_cache(self) -> int:
        """Drop every radix-cache node (slot-held blocks survive under
        the slots' own references). Returns blocks released."""
        released = self._prefix.flush() if self._prefix is not None \
            else 0
        if released:
            # freed blocks must wake a memoised blocked head-of-line
            # request (see _hol_block)
            self._release_epoch += 1
        return released

    def set_prefill_chunk(self, chunk: int) -> bool:
        """Hot-apply the chunked-prefill budget (autotune axis
        ``prefill_chunk``, ISSUE 20).  The scheduler reads
        ``self._chunked`` / ``self.prefill_chunk`` fresh every tick,
        so this is a host-side flag flip — no restart.  A chunk width
        never run before costs one executable compile, paid here when
        the replica is quiesced (live-retune episodes always are) and
        lazily at the next chunk tick otherwise.  Slots currently
        mid-prefill pin the switch: returns False without changing
        anything — retry after they graduate."""
        chunk = int(chunk)
        if chunk < 0:
            raise ValueError(f"prefill_chunk must be >= 0, got {chunk}")
        if chunk == self.prefill_chunk:
            return True
        if any(r is not None and r.prefilling for r in self._slots):
            return False
        self.prefill_chunk = chunk
        self._chunked = chunk > 0
        if self._chunked and self.num_active == 0 and not self._queue:
            self._warmup_chunked()
        return True

    def check_leak_free(self):
        """Drained-engine invariant: with no active slots, no queue and
        a flushed prefix cache, every pool block must be free."""
        assert self.num_active == 0 and not self._queue, \
            "leak check requires a drained engine"
        if self._alloc is not None:
            self.flush_prefix_cache()
            self._alloc.check_leak_free()

    def warmup(self, buckets: Optional[List[int]] = None):
        """Compile (or deserialize from the persistent cache) the decode
        + sampling executables and the given prefill buckets before
        traffic arrives.  Uses slot 0 (dense) / transient pool blocks
        (paged) with throwaway tokens; lengths are reset afterwards so
        the garbage stays masked.  Paged engines with a prefix cache
        also compile the traced-prefix prefill executable per bucket."""
        assert self.num_active == 0 and not self._queue, \
            "warmup() must run before traffic"
        if self._chunked:
            # chunked mode never runs the bucketed prefill executables
            # — admission binds slots and the chunk executable does all
            # prompt work, so that is what warmup compiles
            self._warmup_chunked()
        elif self.kv_layout == "paged":
            self._warmup_paged(buckets)
        else:
            self._warmup_dense(buckets)
        if self._spec is not None:
            # draft prefill per bucket + the spec tick executable; both
            # caches' lengths are zeroed afterwards (inside)
            self._spec.warmup()
        return self

    def _warmup_dense(self, buckets):
        for b in (buckets or [self.buckets[0]]):
            ids = jnp.zeros((1, b), jnp.int32)
            # warmup runs throwaway tokens — its expert-load fold is
            # discarded so the balance stats describe real traffic only
            logits, cache, _ = self._timed_exec(
                "prefill_ms", ("prefill", b), self._prefill_jit,
                self.params, self.cache, ids, np.int32(0), np.int32(1))
            self.cache = cache
        self._key, sub = jax.random.split(self._key)
        self._timed_exec("prefill_ms", ("sample", 1), self._sample_jit,
                         logits, sub, jnp.zeros((1,), jnp.float32),
                         jnp.ones((1,), jnp.float32))
        nxt, self._key, cache, _ = self._timed_exec(
            "decode_ms", ("decode", 0), self._decode_jit,
            self.params, self.cache,
            jnp.zeros(self.batch_slots, jnp.int32),
            jnp.zeros(self.batch_slots, jnp.int32), self._key,
            jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        # drop the warmup garbage: zero every slot's length (host-side
        # constant, so no extra executable rides the hot path).  On a
        # serving mesh the zeros are COMMITTED like the originals —
        # an uncommitted lengths operand would recompile the first
        # real prefill (jit keys on committed-vs-uncommitted shardings)
        zeros = jnp.zeros((self.batch_slots,), jnp.int32)
        if self.mesh is not None:
            try:
                zeros = self._put(self.mesh, zeros, ("dp",))
            except Exception as e:
                self._shard_failed("warmup_lengths", e)
        self.cache = type(cache)(cache.k, cache.v, zeros,
                                 cache.k_scale, cache.v_scale)
        return self

    def _warmup_paged(self, buckets):
        logits = None
        for b in (buckets or [self.buckets[0]]):
            n = blocks_for(b, self.block_size)
            if n > self._alloc.capacity:
                # a bucket bigger than the whole pool is unadmittable
                # (add_request guard) — nothing will ever run it, so
                # there is nothing to warm
                continue
            blocks = self._alloc.alloc(n)
            assert blocks is not None, "warmup needs an empty pool"
            row = np.zeros(self.blocks_per_slot, np.int32)
            row[:n] = blocks
            ids = jnp.zeros((1, b), jnp.int32)
            logits, cache, _ = self._timed_exec(
                "prefill_ms", ("prefill_paged", b),
                self._prefill_paged_cold_jit,
                self.params, self.cache, ids, jnp.asarray(row),
                np.int32(1))
            self.cache = cache
            if self._prefix is not None:
                logits, cache, _ = self._timed_exec(
                    "prefill_ms", ("prefill_paged_ext", b),
                    self._prefill_paged_ext_jit,
                    self.params, self.cache, ids, jnp.asarray(row),
                    np.int32(0), np.int32(1))
                self.cache = cache
            self._alloc.decref(blocks)
        if logits is not None:
            self._key, sub = jax.random.split(self._key)
            self._timed_exec("prefill_ms", ("sample", 1),
                             self._sample_jit, logits, sub,
                             jnp.zeros((1,), jnp.float32),
                             jnp.ones((1,), jnp.float32))
        # decode over all-null tables: every write lands in the null
        # block, every slot length is 0 — pure compile fodder
        nxt, self._key, cache, _ = self._timed_exec(
            "decode_ms", ("decode", 0), self._decode_paged_jit,
            self.params, self.cache,
            jnp.zeros(self.batch_slots, jnp.int32),
            jnp.asarray(self._tables),
            jnp.zeros(self.batch_slots, jnp.int32), self._key,
            jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        self.cache = cache
        return self

    def _warmup_chunked(self):
        """Compile the chunked-mode serving set: the chunk executable,
        the batch-wide graduation sampler, and the decode executable.
        All-zero tokens/advance/lengths over the real cache — the
        garbage writes land above length 0 / in the null block, so
        nothing needs resetting afterwards."""
        c = self.prefill_chunk
        toks = jnp.zeros((self.batch_slots, c), jnp.int32)
        adv = jnp.zeros((self.batch_slots,), jnp.int32)
        lens = jnp.zeros((self.batch_slots,), jnp.int32)
        if self.kv_layout == "paged":
            logits, cache, _ = self._timed_exec(
                "prefill_ms", ("prefill_chunk_paged", c),
                self._prefill_chunk_paged_jit,
                self.params, self.cache, toks,
                jnp.asarray(self._tables), lens, adv)
        else:
            logits, cache, _ = self._timed_exec(
                "prefill_ms", ("prefill_chunk", c),
                self._prefill_chunk_jit,
                self.params, self.cache, toks, lens, adv)
        self.cache = cache
        self._key, sub = jax.random.split(self._key)
        self._timed_exec(
            "prefill_ms", ("sample", self.batch_slots),
            self._sample_jit, logits, sub,
            jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        if self.kv_layout == "paged":
            nxt, self._key, cache, _ = self._timed_exec(
                "decode_ms", ("decode", 0), self._decode_paged_jit,
                self.params, self.cache,
                jnp.zeros(self.batch_slots, jnp.int32),
                jnp.asarray(self._tables),
                jnp.zeros(self.batch_slots, jnp.int32), self._key,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        else:
            nxt, self._key, cache, _ = self._timed_exec(
                "decode_ms", ("decode", 0), self._decode_jit,
                self.params, self.cache,
                jnp.zeros(self.batch_slots, jnp.int32),
                jnp.zeros(self.batch_slots, jnp.int32), self._key,
                jnp.asarray(self._temps), jnp.asarray(self._top_ps))
        self.cache = cache
        return self

    # ---- MoE expert-balance plumbing (ISSUE 19) -----------------------
    def _accum_moe(self, moe):
        """Fold one executable's expert-stats output (or None, the
        dense-model case) into the host counters.  Called at the step's
        existing host-sync point — the arrays are siblings of the
        sampled ids, so fetching them costs no extra sync."""
        if moe is None:
            return
        load = np.asarray(moe["load"], np.float64)
        assigned = float(np.asarray(moe["assigned"]))
        if self._moe_load is None:
            self._moe_load = np.zeros_like(load)
        self._moe_load += load
        self._timings["moe_assigned_tokens"] += assigned
        # capacity overflow: gating assigned top_k slots per token, the
        # capacity buckets kept load.sum() of them — the shortfall is
        # exactly the dropped (overflowed) expert assignments
        self._timings["moe_dropped_tokens"] += max(
            0.0, assigned - float(load.sum()))

    def _moe_expert_param_names(self) -> List[str]:
        """Parameter names of the expert FFN weights (the arrays the
        'ep' axis shards).  The '.experts.' segment is the
        MoELayer/ExpertParallelFFN naming contract; the replicated gate
        is deliberately excluded."""
        return [n for n in self.params if ".experts." in n]

    def _moe_expert_bytes_per_device(self) -> int:
        """PER-DEVICE resident bytes of the expert FFN weights, read
        off the committed arrays' shard shapes (falls back to the
        global shape for host-resident/unsharded arrays)."""
        total = 0
        for name in self._moe_expert_param_names():
            arr = self.params[name]
            shape = arr.shape
            try:
                shape = arr.sharding.shard_shape(arr.shape)
            except Exception:
                pass
            total += int(np.prod(shape)) * jnp.dtype(arr.dtype).itemsize
        return total

    def _decode_hbm_bytes_per_tok(self) -> int:
        """The decode loop's HBM read traffic per generated token, from
        the live shapes (satellite of the megakernel ISSUE: the fused
        kernel's saving must be a reported number, not a claim): every
        step streams the parameters once (amortized over the
        batch_slots tokens it produces) plus each slot's full KV extent
        — int8-aware, counting the 8-bit values AND the f32 scale
        planes the kernels stream alongside them.  Under a tp-sharded
        serving mesh the number is PER SHARD (ISSUE 18): each device
        streams its weight shard and its slice of the KV heads — the
        whole point of tensor-parallel decode is this denominator.
        Expert FFN weights divide by 'ep', not 'tp' (ISSUE 19): a
        device streams only its own expert shard."""
        tp = max(self.tp_degree, 1)
        ep = max(self.ep_degree, 1)
        expert_names = set(self._moe_expert_param_names()) \
            if self._is_moe else set()
        pbytes = 0
        ebytes = 0
        for name, leaf in self.params.items():
            b = int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            if name in expert_names:
                ebytes += b
            else:
                pbytes += b
        pbytes //= tp
        # mirror the sharding helpers: experts replicate when ep does
        # not divide them, and the traffic number must say what runs
        if ep > 1 and self.model.cfg.moe_num_experts % ep == 0:
            ebytes //= ep
        pbytes += ebytes
        cfg = self.model.cfg
        # KV heads split over tp only when they divide evenly (the
        # sharding helpers replicate otherwise — mirror that here)
        hkv = cfg.num_kv_heads // tp if cfg.num_kv_heads % tp == 0 \
            else cfg.num_kv_heads
        kv_item = jnp.dtype(self.cache.k.dtype).itemsize
        if self.kv_layout == "paged":
            per_slot_pos = self.blocks_per_slot * self.block_size
        else:
            per_slot_pos = self.max_seq_len
        kv = (2 * cfg.num_layers * per_slot_pos * hkv *
              cfg.head_dim * kv_item)
        if self.cache.quantized:
            kv += 2 * cfg.num_layers * per_slot_pos * hkv * 4
        return int(pbytes / self.batch_slots + kv)

    @property
    def stats(self) -> dict:
        """Cumulative serving stats (SpmdTrainer.stats convention):
        prefill/decode wall-clock, compile_ms_cold (first call per
        executable), host sync time, tokens/sec over decode wall-clock,
        mean slot occupancy, the process-wide XLA compile/trace deltas
        since engine construction — plus, paged, block-pool occupancy,
        preemptions and radix-cache hit rates, and PER-REQUEST records
        (TTFT / decode tokens/sec) the load harness consumes."""
        t = self._timings
        s = {k: (round(v, 3) if isinstance(v, float) else v)
             for k, v in t.items()}
        steps = max(t["decode_steps"], 1)
        s["slot_occupancy"] = round(t["occupancy_sum"] / steps, 4)
        decode_s = t["decode_ms"] / 1e3
        s["decode_tokens_per_sec"] = round(
            t["tokens_generated"] / decode_s, 2) if decode_s > 0 else None
        s["xla_compiles"] = self._counters0.new_compiles
        s["jaxpr_traces"] = self._counters0.new_traces
        s["compile_cache_dir"] = compile_cache.compile_cache_dir()
        s["batch_slots"] = self.batch_slots
        s["buckets"] = list(self.buckets)
        s["donate"] = self._donate
        s["kv_layout"] = self.kv_layout
        s["kv_dtype"] = self.kv_dtype or "dense"
        # chunked prefill (ISSUE 20): mode + chunk size ride every
        # snapshot (bench rows, loadgen reports, the doctor's
        # 'prefill-stall' rule gates itself off when chunking is on)
        s["chunked_prefill"] = self._chunked
        s["prefill_chunk"] = self.prefill_chunk
        # pod-scale serving (ISSUE 18): tp degree + mesh layout ride
        # every stats snapshot (and through it, bench rows + loadgen
        # reports); the megakernel flag reports what actually runs —
        # it stands down under tp>1 (see gpt._megakernel_active)
        s["tp"] = self.tp_degree
        s["ep"] = self.ep_degree
        if self.mesh is not None:
            s["serving_mesh"] = {str(ax): int(n)
                                 for ax, n in self.mesh.shape.items()}
        # expert-balance observability (ISSUE 19): the load histogram,
        # the capacity-overflow rate, and the max/mean skew the
        # 'expert-imbalance' doctor rule reads.  Dense models drop the
        # moe_* accumulator keys entirely (same convention as spec).
        if self._is_moe:
            s["moe_num_experts"] = int(self.model.cfg.moe_num_experts)
            load = self._moe_load
            s["moe_expert_load"] = (
                [round(float(v), 1) for v in load]
                if load is not None else None)
            assigned = t["moe_assigned_tokens"]
            s["moe_dropped_rate"] = round(
                t["moe_dropped_tokens"] / assigned, 4) if assigned else 0.0
            if load is not None and float(load.sum()) > 0:
                s["moe_load_skew"] = round(
                    float(load.max()) / max(float(load.mean()), 1e-9), 3)
            else:
                s["moe_load_skew"] = None
        else:
            s.pop("moe_assigned_tokens", None)
            s.pop("moe_dropped_tokens", None)
        from ..ops.decode_megakernel import megakernel_enabled
        s["decode_megakernel"] = (megakernel_enabled(self.model.cfg)
                                  and self.tp_degree == 1)
        s["decode_hbm_bytes_per_tok"] = self._decode_hbm_bytes_per_tok()
        if self._spec is not None:
            s["spec_k"] = self._spec.k
            # per (tick × active slot): 1.0 is what plain decode pays a
            # host sync for, K+1 is the ceiling
            ticks = t["spec_slot_ticks"]
            per_tick = t["spec_tokens_committed"] / ticks if ticks else 0.0
            s["accepted_tokens_per_tick"] = round(per_tick, 3)
            s["spec_acceptance_rate"] = round(
                (t["spec_tokens_committed"] - ticks)
                / max(ticks * self._spec.k, 1), 4)
            if ticks:
                # one tick streams the target once (the window pass is
                # byte-wise one decode step) + the draft ~K times, and
                # emits per_tick tokens: the amortized read traffic is
                # the number the ISSUE wants to see drop
                s["decode_hbm_bytes_per_tok"] = int(
                    (s["decode_hbm_bytes_per_tok"]
                     + self._spec.k * self._spec.step_hbm_bytes())
                    / max(per_tick, 1.0))
        else:
            s.pop("spec_ticks", None)
            s.pop("spec_tokens_committed", None)
            s.pop("spec_slot_ticks", None)
            s.pop("spec_capacity_retirements", None)
        if self.kv_layout == "paged":
            s["kv_block_size"] = self.block_size
            s["kv_blocks_total"] = self._alloc.capacity
            s["kv_blocks_in_use"] = self._alloc.num_in_use
            s["block_occupancy"] = round(
                t["block_occupancy_sum"] / steps, 4)
            if self._prefix is not None:
                s.update(self._prefix.stats)
                # the router-facing digest, JSON-safe (fingerprints as a
                # count; the raw set rides prefix_summary())
                s["prefix_cache"] = {
                    k: (len(v) if k == "fingerprints" else v)
                    for k, v in self._prefix.summary().items()}
            s.pop("block_occupancy_sum", None)    # internal accumulator
        else:
            s.pop("block_occupancy_sum", None)
            s.pop("preemptions", None)
            s.pop("memory_capped_retirements", None)
        # per-request latency records, not just aggregates (satellite:
        # the load harness computes its percentiles from these)
        s["per_request"] = dict(self.request_stats)
        # queue-expired (deadline) requests never produced a token and
        # have no TTFT — they are counted, not averaged
        ttfts = [r["ttft_ms"] for r in self.request_stats.values()
                 if r["ttft_ms"] is not None]
        if ttfts:
            p50, p99 = np.percentile(ttfts, [50, 99])
            s["ttft_ms_p50"] = round(float(p50), 3)
            s["ttft_ms_p99"] = round(float(p99), 3)
        # inter-token latency pooled across finished requests — the
        # number chunked prefill exists to fix at the tail (the load
        # harness recomputes these with coordinated-omission lateness
        # folded into each request's first gap)
        gaps = [g for r in self.request_stats.values()
                for g in r.get("itl_gaps_ms") or ()]
        if gaps:
            p50, p99 = np.percentile(gaps, [50, 99])
            s["itl_ms_p50"] = round(float(p50), 3)
            s["itl_ms_p99"] = round(float(p99), 3)
        # executable observatory (ISSUE 15): the per-kind roofline
        # digest for THIS engine's executables — populated once
        # something ran the deferred analyses (bench legs, the report
        # CLI, exec_registry.analyze_all); None until then.  Reading
        # stats never compiles and never syncs.
        s["exec_profile"] = _exec_registry.profile(self._exec_component)
        s["hbm"] = _exec_registry.ledger().snapshot()
        # perf-doctor verdict over the serving signals above
        # (observability.doctor): ranked [{bottleneck, evidence, knob}]
        s["doctor"] = _doctor.diagnose(s, kind="serve")
        return s
