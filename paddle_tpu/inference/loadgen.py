"""Poisson-arrival serving load harness.

``bench.py --serve`` measures the engine under a CLOSED loop: every
request is enqueued up front, so the queue is always full and the only
number that comes out is peak throughput.  Real traffic is OPEN-loop —
requests arrive on their own clock whether or not the server keeps up —
and the metrics that matter are the ones a user feels: time-to-first-
token at the tail (p99), sustained tokens/sec, and how close the
slot/block pools run to exhaustion.  This module drives the engine with
exponential inter-arrival times (a Poisson process at ``rate_rps``) and
reports exactly those, consuming the engine's per-request records
(``InferenceEngine.stats['per_request']``).

Workload shape: ``SharedPrefixWorkload`` mints prompts where a fraction
share a fixed system-prompt prefix — the pattern the radix prefix cache
exists for — so the harness also measures the prefix hit rate it buys.

Everything is host-side scheduling around ``engine.step()``; the
compile-counter discipline applies unchanged (the smoke contract:
a whole Poisson run after warmup = ZERO new XLA compiles).
"""
from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..observability import doctor as _doctor
from ..observability import metrics as _obs_metrics
from ..observability import watchdog as _obs_watchdog
from ..observability.slo import SLOMonitor

__all__ = ["SharedPrefixWorkload", "MultiTenantWorkload", "run_loadtest",
           "run_fleet_loadtest"]


class SharedPrefixWorkload:
    """Prompt generator: with probability ``shared_frac`` a prompt is
    ``system_prefix + random tail``, otherwise fully random.  Tail and
    generation lengths are uniform over the given ranges."""

    def __init__(self, vocab_size: int, seed: int = 0,
                 shared_frac: float = 0.5, prefix_len: int = 16,
                 tail_len=(3, 12), max_new=(4, 12)):
        self._rng = np.random.RandomState(seed)
        self.vocab = int(vocab_size)
        self.shared_frac = float(shared_frac)
        self.tail_len = tail_len
        self.max_new = max_new
        self.system_prefix = self._rng.randint(
            1, self.vocab, (int(prefix_len),)).astype(np.int32)

    def sample(self):
        """Returns (prompt ids, max_new_tokens)."""
        rng = self._rng
        tail = rng.randint(1, self.vocab, (rng.randint(
            self.tail_len[0], self.tail_len[1] + 1),)).astype(np.int32)
        if rng.rand() < self.shared_frac:
            prompt = np.concatenate([self.system_prefix, tail])
        else:
            prompt = tail
        return prompt, int(rng.randint(self.max_new[0],
                                       self.max_new[1] + 1))


def run_loadtest(engine, num_requests: int, rate_rps: float,
                 workload: Optional[SharedPrefixWorkload] = None,
                 seed: int = 0, eos_id: Optional[int] = None,
                 deadline_s: Optional[float] = None,
                 slo_monitor: Optional[SLOMonitor] = None) -> dict:
    """Open-loop Poisson load test against a warmed engine.

    Arrival times are drawn up front (exponential gaps at ``rate_rps``);
    the drive loop enqueues every request whose arrival time has passed,
    then runs ``engine.step()`` — or, when the engine is fully idle,
    sleeps until the next arrival (an open-loop harness must not spin
    the decode batch on an empty engine; that would burn host time the
    real server would spend waiting on the network).

    Returns the report dict: TTFT p50/p99 (enqueue→first token, queueing
    included — that is the point of open loop), per-request decode
    tokens/sec p50, wall-clock tokens/sec, offered vs achieved request
    rate, slot/block occupancy, prefix hit rate, and preemptions.

    `deadline_s` gives every request a per-request deadline (the SLO
    column): requests past it are retired by the engine — slot and
    blocks freed — and counted in the report's ``timed_out_requests``
    instead of wedging a decode slot on an overloaded server.
    """
    workload = workload or SharedPrefixWorkload(
        getattr(engine.model.cfg, "vocab_size", 1 << 15), seed=seed)
    # cumulative engine counters are engine-LIFETIME; snapshot so the
    # report describes THIS window even on a reused engine (the same
    # snapshot-and-subtract bench.py uses for compile counters)
    t_snap = dict(engine._timings)
    _load0 = getattr(engine, "_moe_load", None)
    moe_load_snap = None if _load0 is None else _load0.copy()
    pc = engine._prefix
    # NB: the radix cache defines __len__, so an EMPTY tree is falsy —
    # the None-check must be identity, not truthiness
    pc_snap = (pc.queries, pc.hit_queries, pc.hit_blocks) \
        if pc is not None else None
    rng = np.random.RandomState(seed + 1)
    gaps = rng.exponential(1.0 / float(rate_rps), size=int(num_requests))
    arrivals = np.cumsum(gaps)
    plan = [(t,) + workload.sample() for t in arrivals]

    rids: List[int] = []
    pending = set()
    recs = {}
    # coordinated-omission correction: a request whose Poisson arrival
    # passed while the harness was blocked inside a decode step is
    # enqueued LATE — a real user's clock started at the planned
    # arrival, so that lateness belongs in its TTFT
    late_ms = {}

    def _drain():
        """Consume finished requests as they retire: their stat record
        AND their result leave the engine, so neither the engine's
        bounded per-request history (cap 4096) nor its results dict
        truncates or accumulates over an arbitrarily long run."""
        for r in [r for r in pending if r in engine.request_stats]:
            rec = engine.request_stats.pop(r)
            if rec["ttft_ms"] is not None:
                rec["ttft_ms"] = round(rec["ttft_ms"] + late_ms[r], 3)
            # the same correction for ITL: lateness delays the FIRST
            # inter-token interval the user observes — fold it there so
            # an overloaded harness can't flatter the tail
            gaps = rec.get("itl_gaps_ms")
            if gaps:
                gaps[0] = round(gaps[0] + late_ms[r], 3)
            recs[r] = rec
            engine.results.pop(r, None)
            pending.discard(r)

    t0 = time.perf_counter()
    i = 0
    while i < len(plan) or engine.has_work:
        now = time.perf_counter() - t0
        while i < len(plan) and plan[i][0] <= now:
            arrival_t, prompt, max_new = plan[i]
            rid = engine.add_request(prompt, max_new_tokens=max_new,
                                     eos_id=eos_id,
                                     deadline_s=deadline_s)
            late_ms[rid] = max(
                time.perf_counter() - t0 - arrival_t, 0.0) * 1e3
            rids.append(rid)
            pending.add(rid)
            i += 1
        if engine.has_work:
            # a wedged scheduler raises instead of busy-spinning the
            # harness (the same stall check run()/generate() use)
            engine.step_or_raise()
            _drain()
        elif i < len(plan):
            time.sleep(min(max(plan[i][0] - now, 0.0), 0.05))
    _drain()
    wall_s = time.perf_counter() - t0

    st = engine.stats
    t1 = engine._timings
    steps = max(t1["decode_steps"] - t_snap["decode_steps"], 1)
    recs = [recs[r] for r in rids if r in recs]
    ttfts = [r["ttft_ms"] for r in recs if r["ttft_ms"] is not None]
    dtps = [r["decode_tokens_per_sec"] for r in recs
            if r["decode_tokens_per_sec"]]
    # inter-token latency pooled across requests (per-token samples,
    # the CO-corrected first gaps included) — the tail chunked prefill
    # exists to fix: a monolithic admission freezes every in-flight
    # stream for the length of the longest prompt's prefill
    itl = [g for r in recs for g in r.get("itl_gaps_ms") or ()]
    total_tokens = sum(r["tokens"] for r in recs)
    report = {
        "num_requests": len(recs),
        "offered_rps": round(float(rate_rps), 3),
        "achieved_rps": round(len(recs) / wall_s, 3) if wall_s else None,
        "wall_s": round(wall_s, 3),
        "tokens_generated": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 2)
        if wall_s else None,
        "ttft_ms_p50": round(float(np.percentile(ttfts, 50)), 3)
        if ttfts else None,
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)), 3)
        if ttfts else None,
        "itl_ms_p50": round(float(np.percentile(itl, 50)), 3)
        if itl else None,
        "itl_ms_p99": round(float(np.percentile(itl, 99)), 3)
        if itl else None,
        "decode_tokens_per_sec_p50": round(float(np.percentile(dtps, 50)),
                                           2) if dtps else None,
        "slot_occupancy": round(
            (t1["occupancy_sum"] - t_snap["occupancy_sum"]) / steps, 4),
        "preemptions": (t1["preemptions"] - t_snap["preemptions"])
        if "preemptions" in t_snap else 0,
        # SLO column: how many requests blew their per-request deadline
        "deadline_s": deadline_s,
        "timed_out_requests": sum(
            1 for r in recs if r.get("timed_out")),
        "kv_layout": st["kv_layout"],
    }
    # SLO verdict over THIS window's corrected TTFTs (threshold from
    # PADDLE_TPU_SLO_TTFT_P99_MS / the monitor, regression vs the bench
    # history): the observability tentpole's rolling watch, reported —
    # never asserted — by the harness
    mon = slo_monitor or SLOMonitor()
    for t in ttfts:
        mon.observe(t)
    report["slo"] = mon.check()
    for k in ("kv_block_size", "kv_blocks_total"):
        if k in st:
            report[k] = st[k]
    if engine.kv_layout == "paged":
        report["block_occupancy"] = round(
            (t1["block_occupancy_sum"] - t_snap["block_occupancy_sum"])
            / steps, 4)
    if pc_snap is not None:
        dq = pc.queries - pc_snap[0]
        dh = pc.hit_queries - pc_snap[1]
        report["prefix_queries"] = dq
        report["prefix_hit_rate"] = round(dh / dq, 4) if dq else 0.0
        report["prefix_hit_blocks"] = pc.hit_blocks - pc_snap[2]
    # expert-balance columns (ISSUE 19), WINDOW-scoped like everything
    # else here: per-expert routed-token load, capacity-overflow drop
    # rate, and max/mean skew — the inputs the 'expert-imbalance'
    # doctor rule reads off the merged dict below
    if st.get("moe_num_experts"):
        assigned = (t1["moe_assigned_tokens"]
                    - t_snap.get("moe_assigned_tokens", 0.0))
        dropped = (t1["moe_dropped_tokens"]
                   - t_snap.get("moe_dropped_tokens", 0.0))
        report["moe_num_experts"] = st["moe_num_experts"]
        report["ep"] = st["ep"]
        report["moe_assigned_tokens"] = round(assigned, 1)
        report["moe_dropped_rate"] = round(dropped / assigned, 4) \
            if assigned > 0 else 0.0
        load = getattr(engine, "_moe_load", None)
        if load is not None:
            wload = load - (moe_load_snap if moe_load_snap is not None
                            else 0.0)
            report["moe_expert_load"] = [round(float(v), 1)
                                         for v in wload]
            mean = float(wload.mean())
            report["moe_load_skew"] = round(float(wload.max()) / mean,
                                            3) if mean > 0 else None
    # perf-doctor verdict for the window (observability.doctor): the
    # engine's steady signals with this window's columns layered on top
    merged = {k: v for k, v in st.items()
              if k not in ("per_request", "doctor")}
    merged.update(report)
    report["doctor"] = _doctor.diagnose(merged, kind="serve")
    return report


class MultiTenantWorkload:
    """Skewed multi-tenant traffic: ``num_tenants`` tenants, each with
    its OWN system prefix, arriving with Zipf-ish weights
    (``1/rank^skew``) — a few hot tenants dominate, a long tail of cold
    ones trickles.  This is the workload where a prefix-aware router
    earns its keep: routing a hot tenant's requests to the replica
    already holding its prefix turns N replicas into N *sharded*
    caches instead of N redundant cold ones."""

    def __init__(self, vocab_size: int, seed: int = 0,
                 num_tenants: int = 8, skew: float = 1.2,
                 prefix_len: int = 16, tail_len=(3, 12), max_new=(4, 12)):
        self._rng = np.random.RandomState(seed)
        self.vocab = int(vocab_size)
        self.tail_len = tail_len
        self.max_new = max_new
        self.prefixes = [
            self._rng.randint(1, self.vocab,
                              (int(prefix_len),)).astype(np.int32)
            for _ in range(int(num_tenants))]
        w = 1.0 / np.arange(1, num_tenants + 1) ** float(skew)
        self.weights = w / w.sum()

    def sample(self):
        """Returns (tenant id, prompt ids, max_new_tokens)."""
        rng = self._rng
        tenant = int(rng.choice(len(self.prefixes), p=self.weights))
        tail = rng.randint(1, self.vocab, (rng.randint(
            self.tail_len[0], self.tail_len[1] + 1),)).astype(np.int32)
        prompt = np.concatenate([self.prefixes[tenant], tail])
        return tenant, prompt, int(rng.randint(self.max_new[0],
                                               self.max_new[1] + 1))


def warm_fleet(router, workload, passes: int = 2):
    """Steady-state warmup: run every tenant's prefix through the
    router (closed loop, `passes` rounds) so the measured window that
    follows describes the fleet's STEADY behavior, not its cold start
    — first-touch prefix misses are unavoidable under any policy and
    land here for all of them.  Under a prefix-aware policy this also
    settles each tenant onto its home replica."""
    for _ in range(int(passes)):
        for prefix in workload.prefixes:
            # the prefix itself: admission adopts its full blocks into
            # the radix tree, which is all a later match() consults
            router.add_request(prefix, max_new_tokens=1)
    router.run()
    # consume the warmup traffic's records so the measured window's
    # bookkeeping starts clean
    for r in router.replicas:
        r.results.clear()
        r.request_stats.clear()


def run_fleet_loadtest(router, num_requests: int, rate_rps: float,
                       workload: Optional[MultiTenantWorkload] = None,
                       seed: int = 0, eos_id: Optional[int] = None,
                       deadline_s: Optional[float] = None,
                       slo_monitor: Optional[SLOMonitor] = None,
                       rpc: bool = False) -> dict:
    """Open-loop Poisson load test against a ROUTED fleet (a
    ``router.Router`` over warmed replicas) — the multi-replica twin of
    :func:`run_loadtest`.  Requests arrive on the Poisson clock, the
    router places each one (by prefix overlap, load, or round-robin —
    its policy), and every replica with work advances each drive round.

    The report adds the fleet columns the single-engine harness cannot
    have: per-replica request counts and slot occupancy, the ROUTER hit
    rate (how often cache affinity made the placement), the aggregate
    radix-cache hit rate across replicas (the number cache-aware
    routing is supposed to move), and accepted_tokens_per_tick when the
    replicas decode speculatively.

    Each replica runs on its OWN driver thread (the router only places
    requests; it never serializes the fleet): a replica's prefill work
    delays ITS streams, not the whole fleet — which is both how a real
    deployment behaves and what makes routing quality visible in the
    TTFT tail.  Engines stay single-threaded internally (one driver
    thread each; the main thread only enqueues and reads finished
    records).

    ``rpc=True`` (ISSUE 18 satellite) interposes the socket transport:
    each replica is wrapped in a ``ReplicaRPCServer``, a fresh Router
    over ``RPCReplicaProxy`` clients re-routes the same plan, and
    every placement, summary scrape and engine step crosses the
    length-prefixed JSON protocol — the wire contract replicas in
    separate processes would speak."""
    if rpc:
        from .router import ReplicaRPCServer, RPCReplicaProxy
        from .router import Router as _Router
        servers = [ReplicaRPCServer(r).start() for r in router.replicas]
        proxies = [RPCReplicaProxy(s.address) for s in servers]
        rpc_router = _Router(proxies, policy=router.policy,
                             max_load_gap=router.max_load_gap)
        try:
            report = run_fleet_loadtest(
                rpc_router, num_requests, rate_rps, workload=workload,
                seed=seed, eos_id=eos_id, deadline_s=deadline_s,
                slo_monitor=slo_monitor)
        finally:
            for p in proxies:
                p.close()
            for s in servers:
                s.stop()
        report["rpc"] = True
        return report
    replicas = router.replicas
    workload = workload or MultiTenantWorkload(
        getattr(replicas[0].model.cfg, "vocab_size", 1 << 15), seed=seed)
    t_snaps = [dict(r._timings) for r in replicas]
    pcs = [r._prefix for r in replicas]
    pc_snaps = [(pc.queries, pc.hit_queries, pc.hit_blocks)
                if pc is not None else None for pc in pcs]
    # router counters are router-LIFETIME (warm_fleet routes traffic
    # through them too): snapshot so the report describes THIS window
    rt_snap = (router.requests, router.prefix_routed, list(router.routed))
    rng = np.random.RandomState(seed + 1)
    gaps = rng.exponential(1.0 / float(rate_rps), size=int(num_requests))
    arrivals = np.cumsum(gaps)
    plan = [(t,) + workload.sample() for t in arrivals]

    pending = {}                  # (ridx, rid) -> arrival lateness ms
    order: List[tuple] = []
    recs = {}
    tenants = {}
    # fleet aggregation: the harness consumes records out of the
    # replicas (bounded history), so IT is the scrape point — corrected
    # TTFTs flow into the fleet histogram + SLO monitor as they retire
    mon = slo_monitor or SLOMonitor()
    m_ttft = _obs_metrics.histogram(
        "fleet_ttft_ms", "per-request time to first token",
        labels=("replica",))
    m_tokens = _obs_metrics.counter(
        "fleet_tokens_total", "generated tokens", labels=("replica",))

    def _drain():
        for key in [k for k in pending if k[1] in
                    replicas[k[0]].request_stats]:
            ridx, rid = key
            rec = replicas[ridx].request_stats.pop(rid)
            if rec["ttft_ms"] is not None:
                rec["ttft_ms"] = round(rec["ttft_ms"] + pending[key], 3)
                m_ttft.labels(replica=str(ridx)).observe(rec["ttft_ms"])
                mon.observe(rec["ttft_ms"])
            gaps = rec.get("itl_gaps_ms")
            if gaps:
                # arrival lateness delays the first observed
                # inter-token interval, same correction as TTFT
                gaps[0] = round(gaps[0] + pending[key], 3)
            m_tokens.labels(replica=str(ridx)).inc(rec.get("tokens", 0))
            rec["replica"] = ridx
            recs[key] = rec
            replicas[ridx].results.pop(rid, None)
            del pending[key]

    import threading
    stop = threading.Event()
    errors: List[BaseException] = []
    # engines are single-threaded by contract; the harness provides the
    # exclusion: each replica's step and its admissions share one lock
    # (a step's queue sweep iterates the deque an arrival would mutate)
    locks = {id(r): threading.Lock() for r in replicas}

    def _drive(replica):
        # one thread per replica: step while there is work, otherwise
        # yield — mirrors N independent serving processes
        lock = locks[id(replica)]
        try:
            while not stop.is_set():
                if replica.has_work:
                    with lock:
                        replica.step_or_raise()
                else:
                    time.sleep(0.001)
        except BaseException as e:  # surface replica crashes to caller
            errors.append(e)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=_drive, args=(r,), daemon=True)
               for r in replicas]
    for th in threads:
        th.start()
    i = 0
    try:
        while i < len(plan) or router.has_work or pending:
            if errors:
                raise errors[0]
            now = time.perf_counter() - t0
            while i < len(plan) and plan[i][0] <= now:
                arrival_t, tenant, prompt, max_new = plan[i]
                # route outside the lock (reads only), enqueue inside
                ridx = router.route(prompt)
                with locks[id(replicas[ridx])]:
                    rid = replicas[ridx].add_request(
                        prompt, max_new_tokens=max_new, eos_id=eos_id,
                        deadline_s=deadline_s)
                late = max(time.perf_counter() - t0 - arrival_t,
                           0.0) * 1e3
                pending[(ridx, rid)] = late
                order.append((ridx, rid))
                tenants[(ridx, rid)] = tenant
                i += 1
            _drain()
            if i < len(plan):
                time.sleep(min(max(plan[i][0] - now, 0.0), 0.005))
            else:
                time.sleep(0.001)
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=10.0)
    _drain()
    wall_s = time.perf_counter() - t0

    recs_l = [recs[k] for k in order if k in recs]
    ttfts = [r["ttft_ms"] for r in recs_l if r["ttft_ms"] is not None]
    itl = [g for r in recs_l for g in r.get("itl_gaps_ms") or ()]
    total_tokens = sum(r["tokens"] for r in recs_l)
    # per-replica occupancy + aggregate prefix hit rate over THIS window
    occ = []
    steps_total = 0
    preemptions = 0
    pq = ph = 0
    spec_committed = spec_slot_ticks = 0
    moe_assigned = moe_dropped = 0.0
    tick_ms: List[Optional[float]] = []
    for r, snap, pc, pcs0 in zip(replicas, t_snaps, pcs, pc_snaps):
        t1 = r._timings
        d_steps = t1["decode_steps"] - snap["decode_steps"]
        steps = max(d_steps, 1)
        steps_total += d_steps
        occ.append(round(
            (t1["occupancy_sum"] - snap["occupancy_sum"]) / steps, 4))
        # per-replica mean decode-tick wall time over THIS window — the
        # straggler detector's input
        tick_ms.append((t1["decode_ms"] - snap["decode_ms"]) / d_steps
                       if d_steps > 0 else None)
        preemptions += t1.get("preemptions", 0) - snap.get("preemptions",
                                                           0)
        spec_committed += t1["spec_tokens_committed"] - \
            snap["spec_tokens_committed"]
        spec_slot_ticks += t1["spec_slot_ticks"] - snap["spec_slot_ticks"]
        moe_assigned += (t1.get("moe_assigned_tokens", 0.0)
                         - snap.get("moe_assigned_tokens", 0.0))
        moe_dropped += (t1.get("moe_dropped_tokens", 0.0)
                        - snap.get("moe_dropped_tokens", 0.0))
        if pcs0 is not None:
            pq += pc.queries - pcs0[0]
            ph += pc.hit_queries - pcs0[1]
    report = {
        "num_requests": len(recs_l),
        "num_replicas": len(replicas),
        "policy": router.policy,
        "offered_rps": round(float(rate_rps), 3),
        "achieved_rps": round(len(recs_l) / wall_s, 3) if wall_s else None,
        "wall_s": round(wall_s, 3),
        "tokens_generated": total_tokens,
        "tokens_per_sec": round(total_tokens / wall_s, 2)
        if wall_s else None,
        "ttft_ms_p50": round(float(np.percentile(ttfts, 50)), 3)
        if ttfts else None,
        "ttft_ms_p99": round(float(np.percentile(ttfts, 99)), 3)
        if ttfts else None,
        "itl_ms_p50": round(float(np.percentile(itl, 50)), 3)
        if itl else None,
        "itl_ms_p99": round(float(np.percentile(itl, 99)), 3)
        if itl else None,
        "replica_occupancy": occ,
        "requests_per_replica": [n - n0 for n, n0 in
                                 zip(router.routed, rt_snap[2])],
        "router_hit_rate": round(
            (router.prefix_routed - rt_snap[1]) /
            max(router.requests - rt_snap[0], 1), 4),
        "prefix_queries": pq,
        "prefix_hit_rate": round(ph / pq, 4) if pq else 0.0,
        "preemptions": preemptions,
        "deadline_s": deadline_s,
        "timed_out_requests": sum(1 for r in recs_l if r.get("timed_out")),
        "decode_steps": steps_total,
        "tenants_seen": len(set(tenants.values())),
    }
    if spec_slot_ticks:
        report["accepted_tokens_per_tick"] = round(
            spec_committed / spec_slot_ticks, 3)
    if moe_assigned:
        # fleet-aggregate expert balance (ISSUE 19): routed-token and
        # overflow totals summed over the window across replicas
        report["moe_assigned_tokens"] = round(moe_assigned, 1)
        report["moe_dropped_rate"] = round(moe_dropped / moe_assigned, 4)
    # straggler verdict: per-replica tick-time skew vs the fleet median
    # (observability.watchdog; PADDLE_TPU_STRAGGLER_FACTOR) — a routed
    # fleet is only as fast as its slowest member, so the report says
    # WHICH member that is instead of burying it in a mean
    report["straggler"] = _obs_watchdog.detect_stragglers(tick_ms)
    # rolling SLO verdict for the fleet window (breach + regression
    # flags; reported, never asserted)
    report["slo"] = mon.check()
    # perf-doctor verdict over the fleet columns (prefix hit rate,
    # preemptions, spec acceptance — the serving rule table)
    report["doctor"] = _doctor.diagnose(report, kind="serve")
    return report
