"""Inference entry points.

Reference: paddle/fluid/inference/ (AnalysisPredictor + capi/).  The
TPU-native predictor is jit.load's TranslatedLayer over a serialized
StableHLO export; this package adds the C ABI around it (capi/) so
non-Python serving stacks can load the same artifact.
"""
from ..jit.api import load as load_predictor  # noqa: F401
from .disagg import DisaggServingEngine, PrefillWorker  # noqa: F401
from .engine import (  # noqa: F401
    InferenceEngine, Request, default_prefill_buckets)
from .paged_kv import (  # noqa: F401
    BlockAllocator, PagedKVCache, blocks_for, init_paged_cache)
from .prefix_cache import RadixPrefixCache, score_overlap  # noqa: F401
from .router import Router  # noqa: F401
from .spec_decode import SpecDecoder  # noqa: F401

__all__ = ["load_predictor", "InferenceEngine", "Request",
           "default_prefill_buckets", "PagedKVCache", "BlockAllocator",
           "RadixPrefixCache", "blocks_for", "init_paged_cache",
           "Router", "SpecDecoder", "DisaggServingEngine",
           "PrefillWorker", "score_overlap"]
