/* C inference API over the StableHLO export.
 *
 * Reference: paddle/fluid/inference/capi/pd_predictor.cc + paddle_c_api.h
 * (PD_NewAnalysisConfig / PD_PredictorRun family).  This header is the
 * TPU-native equivalent: the predictor behind it is a deserialized
 * StableHLO program executed by XLA, reached through an embedded CPython
 * (XLA itself is the runtime; Python is only the loader glue).
 *
 * Contract:
 *  - PD_NewPredictor loads "<path>.pdmodel" + "<path>.pdiparams"
 *    (paddle.jit.save artifacts).  PYTHONPATH must let the embedded
 *    interpreter import paddle_tpu.
 *  - Inputs are caller-owned buffers; outputs are library-allocated and
 *    released with PD_TensorsFree.
 *  - All functions return NULL / nonzero on failure; PD_GetLastError
 *    returns a static description of the most recent failure.
 */
#ifndef PD_INFERENCE_H
#define PD_INFERENCE_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define PD_MAX_DIMS 8

typedef struct {
    void *data;               /* element buffer                      */
    int64_t shape[PD_MAX_DIMS];
    int32_t ndim;
    char dtype[16];           /* numpy name: "float32", "int32", ... */
} PD_Tensor;

typedef struct PD_Predictor PD_Predictor;

PD_Predictor *PD_NewPredictor(const char *model_path);
void PD_DeletePredictor(PD_Predictor *pred);

/* Runs the exported program. Returns 0 on success and fills *outputs
 * (malloc'd array of *n_outputs tensors, each with a malloc'd data
 * buffer). */
int PD_PredictorRun(PD_Predictor *pred,
                    const PD_Tensor *inputs, int32_t n_inputs,
                    PD_Tensor **outputs, int32_t *n_outputs);

void PD_TensorsFree(PD_Tensor *tensors, int32_t n);

/* Native TRAINING entry (reference fluid/train/demo): loads
 * "<path>.pdtrain" (serialized StableHLO fwd+bwd+update step from
 * SpmdTrainer.export_train_step) + "<path>.pdtrainstate".  Each
 * PD_TrainerStep consumes one (inputs..., labels...) batch and writes
 * the scalar loss. */
typedef struct PD_Trainer PD_Trainer;

PD_Trainer *PD_NewTrainer(const char *model_path);
int PD_TrainerStep(PD_Trainer *trainer,
                   const PD_Tensor *batch, int32_t n_batch,
                   float *loss_out);
void PD_DeleteTrainer(PD_Trainer *trainer);

const char *PD_GetLastError(void);

#ifdef __cplusplus
}
#endif

#endif /* PD_INFERENCE_H */
