/* Standalone C serving demo: load a jit.save export and run it.
 *
 * Usage: pd_capi_demo <model_path> <n_floats>
 * Feeds [1, n] ramp input, prints the output values — proving a
 * non-Python program can serve the StableHLO export (the role of the
 * reference's capi tests / C predictor demos).
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pd_inference.h"

int main(int argc, char **argv) {
    if (argc < 3) {
        fprintf(stderr, "usage: %s <model_path> <n_inputs>\n", argv[0]);
        return 2;
    }
    const char *path = argv[1];
    int n = atoi(argv[2]);

    PD_Predictor *pred = PD_NewPredictor(path);
    if (!pred) {
        fprintf(stderr, "load failed: %s\n", PD_GetLastError());
        return 1;
    }

    float *buf = (float *)malloc(sizeof(float) * (size_t)n);
    for (int i = 0; i < n; i++) buf[i] = (float)i * 0.1f;

    PD_Tensor in;
    memset(&in, 0, sizeof(in));
    in.data = buf;
    in.ndim = 2;
    in.shape[0] = 1;
    in.shape[1] = n;
    snprintf(in.dtype, sizeof(in.dtype), "float32");

    PD_Tensor *outs = NULL;
    int32_t n_outs = 0;
    if (PD_PredictorRun(pred, &in, 1, &outs, &n_outs) != 0) {
        fprintf(stderr, "run failed: %s\n", PD_GetLastError());
        return 1;
    }

    for (int t = 0; t < n_outs; t++) {
        int64_t numel = 1;
        for (int d = 0; d < outs[t].ndim; d++) numel *= outs[t].shape[d];
        printf("OUT %d dtype=%s numel=%lld:", t, outs[t].dtype,
               (long long)numel);
        if (!strcmp(outs[t].dtype, "float32")) {
            const float *v = (const float *)outs[t].data;
            for (int64_t i = 0; i < numel && i < 8; i++)
                printf(" %.6f", v[i]);
        }
        printf("\n");
    }

    PD_TensorsFree(outs, n_outs);
    free(buf);
    PD_DeletePredictor(pred);
    printf("CAPI-DEMO-OK\n");
    return 0;
}
