/* Standalone C TRAINING demo (reference fluid/train/demo/demo_trainer.cc:
 * load a saved train program, feed batches, watch the loss fall).
 *
 * Usage: pd_capi_train_demo <model_path> <n_features> <batch>
 * The model is an exported SpmdTrainer step on a regression net; we
 * feed a fixed synthetic batch and print the loss per step.
 */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#include "pd_inference.h"

int main(int argc, char **argv) {
    if (argc < 4) {
        fprintf(stderr, "usage: %s <model_path> <n_feat> <batch>\n",
                argv[0]);
        return 2;
    }
    const char *path = argv[1];
    int nf = atoi(argv[2]);
    int bs = atoi(argv[3]);

    PD_Trainer *tr = PD_NewTrainer(path);
    if (!tr) {
        fprintf(stderr, "load failed: %s\n", PD_GetLastError());
        return 1;
    }

    /* deterministic synthetic batch: y = sum(x) */
    float *x = (float *)malloc(sizeof(float) * (size_t)(bs * nf));
    float *y = (float *)malloc(sizeof(float) * (size_t)bs);
    for (int i = 0; i < bs; i++) {
        float s = 0.0f;
        for (int j = 0; j < nf; j++) {
            float v = (float)((i * 31 + j * 17) % 13) / 13.0f - 0.5f;
            x[i * nf + j] = v;
            s += v;
        }
        y[i] = s;
    }

    PD_Tensor batch[2];
    memset(batch, 0, sizeof(batch));
    batch[0].data = x;
    batch[0].ndim = 2;
    batch[0].shape[0] = bs;
    batch[0].shape[1] = nf;
    snprintf(batch[0].dtype, sizeof(batch[0].dtype), "float32");
    batch[1].data = y;
    batch[1].ndim = 2;
    batch[1].shape[0] = bs;
    batch[1].shape[1] = 1;
    snprintf(batch[1].dtype, sizeof(batch[1].dtype), "float32");

    float first = 0.0f, loss = 0.0f;
    for (int step = 0; step < 20; step++) {
        if (PD_TrainerStep(tr, batch, 2, &loss) != 0) {
            fprintf(stderr, "step failed: %s\n", PD_GetLastError());
            return 1;
        }
        if (step == 0) first = loss;
        printf("STEP %d loss %.6f\n", step, loss);
    }

    PD_DeleteTrainer(tr);
    free(x);
    free(y);
    if (!(loss < first)) {
        fprintf(stderr, "loss did not decrease: %.6f -> %.6f\n", first,
                loss);
        return 1;
    }
    printf("CAPI-TRAIN-OK first=%.6f last=%.6f\n", first, loss);
    return 0;
}
