/* C inference API implementation: embeds CPython and drives
 * paddle_tpu.inference.capi_bridge (create/run/destroy).
 *
 * Reference: paddle/fluid/inference/capi/pd_predictor.cc wraps the C++
 * AnalysisPredictor; here the predictor is XLA executing a deserialized
 * StableHLO export, and CPython is the loader.  Only bytes + shapes +
 * dtype names cross the C/Python boundary (no numpy C API).
 */
#include "pd_inference.h"

#include <Python.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

static char g_err[512];

static void set_err_from_py(const char *where) {
    PyObject *type = NULL, *value = NULL, *tb = NULL;
    PyErr_Fetch(&type, &value, &tb);
    PyObject *s = value ? PyObject_Str(value) : NULL;
    snprintf(g_err, sizeof(g_err), "%s: %s", where,
             s ? PyUnicode_AsUTF8(s) : "unknown python error");
    Py_XDECREF(s);
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

struct PD_Predictor {
    long long handle;
};

static PyObject *bridge(void) {
    /* import inside the GIL; cached by CPython's module registry */
    PyObject *m = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (!m) set_err_from_py("import paddle_tpu.inference.capi_bridge");
    return m;
}

static int ensure_python(void) {
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        if (!Py_IsInitialized()) {
            snprintf(g_err, sizeof(g_err), "Py_Initialize failed");
            return -1;
        }
    }
    return 0;
}

const char *PD_GetLastError(void) { return g_err; }

PD_Predictor *PD_NewPredictor(const char *model_path) {
    if (ensure_python() != 0) return NULL;
    PyGILState_STATE st = PyGILState_Ensure();
    PD_Predictor *out = NULL;
    PyObject *m = bridge();
    if (m) {
        PyObject *h = PyObject_CallMethod(m, "create", "s", model_path);
        if (h) {
            out = (PD_Predictor *)malloc(sizeof(PD_Predictor));
            out->handle = PyLong_AsLongLong(h);
            Py_DECREF(h);
        } else {
            set_err_from_py("PD_NewPredictor");
        }
        Py_DECREF(m);
    }
    PyGILState_Release(st);
    return out;
}

void PD_DeletePredictor(PD_Predictor *pred) {
    if (!pred) return;
    if (Py_IsInitialized()) {
        PyGILState_STATE st = PyGILState_Ensure();
        PyObject *m = bridge();
        if (m) {
            PyObject *r = PyObject_CallMethod(m, "destroy", "L",
                                              pred->handle);
            Py_XDECREF(r);
            Py_DECREF(m);
        }
        PyGILState_Release(st);
    }
    free(pred);
}

static int64_t numel(const PD_Tensor *t) {
    int64_t n = 1;
    for (int i = 0; i < t->ndim; i++) n *= t->shape[i];
    return n;
}

static int dtype_size(const char *name) {
    if (!strcmp(name, "float32") || !strcmp(name, "int32") ||
        !strcmp(name, "uint32")) return 4;
    if (!strcmp(name, "float64") || !strcmp(name, "int64") ||
        !strcmp(name, "uint64")) return 8;
    if (!strcmp(name, "float16") || !strcmp(name, "bfloat16") ||
        !strcmp(name, "int16")) return 2;
    if (!strcmp(name, "int8") || !strcmp(name, "uint8") ||
        !strcmp(name, "bool")) return 1;
    return -1;
}

int PD_PredictorRun(PD_Predictor *pred,
                    const PD_Tensor *inputs, int32_t n_inputs,
                    PD_Tensor **outputs, int32_t *n_outputs) {
    if (!pred || ensure_python() != 0) return -1;
    int rc = -1;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *m = NULL, *args_list = NULL, *res = NULL;

    m = bridge();
    if (!m) goto done;

    args_list = PyList_New(n_inputs);
    for (int i = 0; i < n_inputs; i++) {
        const PD_Tensor *t = &inputs[i];
        int isz = dtype_size(t->dtype);
        if (isz < 0 || t->ndim > PD_MAX_DIMS) {
            snprintf(g_err, sizeof(g_err),
                     "input %d: bad dtype %s or ndim %d", i, t->dtype,
                     t->ndim);
            goto done;
        }
        PyObject *raw = PyBytes_FromStringAndSize(
            (const char *)t->data, (Py_ssize_t)(numel(t) * isz));
        PyObject *shape = PyTuple_New(t->ndim);
        for (int d = 0; d < t->ndim; d++)
            PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t->shape[d]));
        PyObject *dtype = PyUnicode_FromString(t->dtype);
        if (!raw || !shape || !dtype) {
            Py_XDECREF(raw);
            Py_XDECREF(shape);
            Py_XDECREF(dtype);
            set_err_from_py("PD_PredictorRun: input marshal");
            goto done;
        }
        PyObject *trip = PyTuple_Pack(3, raw, shape, dtype);
        Py_DECREF(raw);
        Py_DECREF(shape);
        Py_DECREF(dtype);
        PyList_SET_ITEM(args_list, i, trip); /* steals trip */
    }

    res = PyObject_CallMethod(m, "run", "LO", pred->handle, args_list);
    if (!res) {
        set_err_from_py("PD_PredictorRun");
        goto done;
    }

    {
        Py_ssize_t n = PyList_Size(res);
        PD_Tensor *outs = (PD_Tensor *)calloc((size_t)n,
                                              sizeof(PD_Tensor));
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *trip = PyList_GetItem(res, i);     /* borrowed */
            PyObject *raw = PyTuple_GetItem(trip, 0);
            PyObject *shape = PyTuple_GetItem(trip, 1);
            PyObject *dtype = PyTuple_GetItem(trip, 2);
            PD_Tensor *t = &outs[i];
            t->ndim = (int32_t)PyTuple_Size(shape);
            for (int d = 0; d < t->ndim && d < PD_MAX_DIMS; d++)
                t->shape[d] = PyLong_AsLongLong(
                    PyTuple_GetItem(shape, d));
            snprintf(t->dtype, sizeof(t->dtype), "%s",
                     PyUnicode_AsUTF8(dtype));
            Py_ssize_t nbytes = PyBytes_Size(raw);
            t->data = malloc((size_t)nbytes);
            memcpy(t->data, PyBytes_AsString(raw), (size_t)nbytes);
        }
        *outputs = outs;
        *n_outputs = (int32_t)n;
        rc = 0;
    }

done:
    Py_XDECREF(res);
    Py_XDECREF(args_list);
    Py_XDECREF(m);
    PyGILState_Release(st);
    return rc;
}

void PD_TensorsFree(PD_Tensor *tensors, int32_t n) {
    if (!tensors) return;
    for (int i = 0; i < n; i++) free(tensors[i].data);
    free(tensors);
}

/* ---- training entry ---------------------------------------------------- */
struct PD_Trainer {
    long long handle;
};

PD_Trainer *PD_NewTrainer(const char *model_path) {
    if (ensure_python() != 0) return NULL;
    PyGILState_STATE st = PyGILState_Ensure();
    PD_Trainer *out = NULL;
    PyObject *m = bridge();
    if (m) {
        PyObject *h = PyObject_CallMethod(m, "create_trainer", "s",
                                          model_path);
        if (h) {
            out = (PD_Trainer *)malloc(sizeof(PD_Trainer));
            out->handle = PyLong_AsLongLong(h);
            Py_DECREF(h);
        } else {
            set_err_from_py("PD_NewTrainer");
        }
        Py_DECREF(m);
    }
    PyGILState_Release(st);
    return out;
}

int PD_TrainerStep(PD_Trainer *trainer,
                   const PD_Tensor *batch, int32_t n_batch,
                   float *loss_out) {
    if (!trainer || ensure_python() != 0) return -1;
    int rc = -1;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *m = NULL, *args_list = NULL, *res = NULL;

    m = bridge();
    if (!m) goto done;

    args_list = PyList_New(n_batch);
    for (int i = 0; i < n_batch; i++) {
        const PD_Tensor *t = &batch[i];
        int isz = dtype_size(t->dtype);
        if (isz < 0 || t->ndim > PD_MAX_DIMS) {
            snprintf(g_err, sizeof(g_err),
                     "batch %d: bad dtype %s or ndim %d", i, t->dtype,
                     t->ndim);
            goto done;
        }
        PyObject *raw = PyBytes_FromStringAndSize(
            (const char *)t->data, (Py_ssize_t)(numel(t) * isz));
        PyObject *shape = PyTuple_New(t->ndim);
        for (int d = 0; d < t->ndim; d++)
            PyTuple_SET_ITEM(shape, d, PyLong_FromLongLong(t->shape[d]));
        PyObject *dtype = PyUnicode_FromString(t->dtype);
        if (!raw || !shape || !dtype) {
            Py_XDECREF(raw);
            Py_XDECREF(shape);
            Py_XDECREF(dtype);
            set_err_from_py("PD_TrainerStep: input marshal");
            goto done;
        }
        PyObject *trip = PyTuple_Pack(3, raw, shape, dtype);
        Py_DECREF(raw);
        Py_DECREF(shape);
        Py_DECREF(dtype);
        PyList_SET_ITEM(args_list, i, trip);
    }

    res = PyObject_CallMethod(m, "trainer_step", "LO", trainer->handle,
                              args_list);
    if (!res) {
        set_err_from_py("PD_TrainerStep");
        goto done;
    }
    {
        PyObject *raw = PyTuple_GetItem(res, 0);
        float v = 0.0f;
        memcpy(&v, PyBytes_AsString(raw),
               sizeof(float) < (size_t)PyBytes_Size(raw)
                   ? sizeof(float) : (size_t)PyBytes_Size(raw));
        if (loss_out) *loss_out = v;
        rc = 0;
    }

done:
    Py_XDECREF(res);
    Py_XDECREF(args_list);
    Py_XDECREF(m);
    PyGILState_Release(st);
    return rc;
}

void PD_DeleteTrainer(PD_Trainer *trainer) {
    if (!trainer) return;
    if (Py_IsInitialized()) {
        PyGILState_STATE st = PyGILState_Ensure();
        PyObject *m = bridge();
        if (m) {
            PyObject *r = PyObject_CallMethod(m, "destroy_trainer", "L",
                                              trainer->handle);
            Py_XDECREF(r);
            Py_DECREF(m);
        }
        PyGILState_Release(st);
    }
    free(trainer);
}
