"""Build the C inference library (and optionally a demo binary).

Reference: paddle/fluid/inference/capi built into libpaddle_fluid_c.so
by cmake; here one cc invocation with python3-config's embed flags.
"""
from __future__ import annotations

import os
import shutil
import subprocess
import sysconfig

HERE = os.path.dirname(os.path.abspath(__file__))


def _embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return ([f"-I{inc}"],
            [f"-L{libdir}", f"-lpython{ver}", "-ldl", "-lm"])


def build_library(output: str | None = None) -> str:
    """Compile pd_inference.c -> libpd_inference.so. Returns the path."""
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None:
        raise RuntimeError("no C compiler found (need cc/gcc/g++)")
    out = output or os.path.join(HERE, "libpd_inference.so")
    incs, libs = _embed_flags()
    cmd = [cc, "-O2", "-fPIC", "-shared",
           os.path.join(HERE, "pd_inference.c"), "-o", out,
           *incs, *libs]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


def build_demo(output: str | None = None,
               source: str = "capi_demo.c") -> str:
    """Compile a standalone C demo executable (capi_demo.c for
    inference, capi_train_demo.c for the native training entry)."""
    cc = shutil.which("cc") or shutil.which("gcc") or shutil.which("g++")
    if cc is None:
        raise RuntimeError("no C compiler found")
    out = output or os.path.join(
        HERE, os.path.splitext(source)[0].replace("capi_", "pd_capi_"))
    incs, libs = _embed_flags()
    cmd = [cc, "-O2", os.path.join(HERE, source),
           os.path.join(HERE, "pd_inference.c"), "-o", out,
           f"-I{HERE}", *incs, *libs]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    return out


if __name__ == "__main__":
    print(build_library())
