"""Radix prefix cache: shared system prompts prefill once.

SGLang's observation (Zheng et al., *Efficiently Programming Large
Language Models using SGLang*): serving traffic is dominated by
requests sharing long prompt prefixes (system prompts, few-shot
preambles, chat history), so the KV cache of those prefixes should be
COMPUTED ONCE and shared — which the paged layout makes trivial,
because sharing a prefix is just pointing two block tables at the same
pool blocks and bumping refcounts.

The tree here is a radix tree over token sequences at BLOCK
granularity: each node owns exactly one pool block and is keyed by that
block's ``block_size``-token chunk (a fixed-width edge label — the
radix compression unit is the KV block, since sub-block sharing cannot
be expressed in a block table anyway).  Matching a new prompt walks the
tree chunk by chunk; every matched node's block goes straight into the
request's block table and its refcount is bumped, so prefill runs only
over the DIVERGENT SUFFIX.  Because matching stops at the first
non-equal chunk, a diverging request simply gets fresh blocks for its
suffix — copy-on-write at block granularity falls out of never handing
out writable references to shared blocks.

Lifetime: the tree itself holds one reference on every node's block
(allocator refcount), independent of any slot.  When the pool runs dry
the scheduler calls :meth:`evict`, which walks leaves in LRU order and
frees only blocks nobody else references — blocks pinned by an active
slot are skipped (their node stays so the slot's retirement returns
them to a still-cached state).  ``PADDLE_TPU_PREFIX_CACHE=0`` disables
the whole thing (the engine then never constructs one).
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

__all__ = ["RadixPrefixCache", "fingerprint_chain", "path_fingerprint",
           "score_overlap"]


def fingerprint_chain(tokens, block_size: int):
    """The rolling path fingerprints of ``tokens``'s full-block prefix
    chunks (capped at len-1, mirroring ``match()`` — at least one token
    is always left to prefill).  Depends only on (tokens, block_size),
    so a router scoring N replicas computes it ONCE and intersects each
    replica's fingerprint set against it."""
    bs = int(block_size)
    toks = [int(t) for t in tokens]
    usable = (len(toks) - 1) // bs
    chain = []
    h = 0
    for i in range(usable):
        h = path_fingerprint(h, tuple(toks[i * bs:(i + 1) * bs]))
        chain.append(h)
    return chain


def score_overlap(tokens, summary: dict, chain=None) -> int:
    """Blocks of ``tokens``'s prefix present in a replica ``summary()``
    digest: consecutive fingerprint-chain matches from the root — the
    score equals the block count match() would return on that replica.
    ``chain`` short-circuits the rolling hash with a precomputed
    ``fingerprint_chain(tokens, summary['block_size'])`` (the router
    scores N replicas against one prompt)."""
    fps = summary["fingerprints"]
    if chain is None:
        chain = fingerprint_chain(tokens, summary["block_size"])
    score = 0
    for h in chain:
        if h not in fps:
            break
        score += 1
    return score


class _Node:
    __slots__ = ("key", "block", "children", "parent", "last_used",
                 "path_hash")

    def __init__(self, key: Optional[tuple], block: Optional[int],
                 parent: Optional["_Node"]):
        self.key = key            # block_size-token tuple (None at root)
        self.block = block        # pool block id (None at root)
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.last_used = 0
        # rolling hash of the root->node chunk path (see path_fingerprint):
        # what the router matches against without ever seeing the tree
        self.path_hash = 0


def path_fingerprint(parent_hash: int, chunk: tuple) -> int:
    """Rolling fingerprint of a chunk path: hash of (parent fingerprint,
    chunk).  Stable within a process (tuple/int hashing), cheap to roll
    forward token-block by token-block — the router recomputes it over
    an incoming prompt and intersects with replica summaries, so two
    sides agree on 'same prefix' iff the chunk paths are equal."""
    return hash((parent_hash, chunk))


class RadixPrefixCache:
    """Block-granular radix tree over token prefixes.

    The cache owns one allocator reference per node; `match` hands the
    caller block ids to alias into a slot's table (the caller increfs
    them for the slot's own lifetime), `insert` adopts a freshly
    prefilled slot's blocks into the tree, `evict` trims LRU leaves
    whose blocks nobody else holds.
    """

    def __init__(self, allocator, block_size: int):
        self._alloc = allocator
        self.block_size = int(block_size)
        self._root = _Node(None, None, None)
        self._nodes = 0
        self._clock = itertools.count(1)
        # block-granular fingerprint index: the path hash of every live
        # node, maintained INCREMENTALLY on insert/evict so summary()
        # never walks the tree (it sits on the router's per-request
        # scoring path)
        self._fingerprints: set = set()
        # stats the engine/load harness report
        self.queries = 0
        self.hit_queries = 0
        self.hit_blocks = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0

    def __len__(self):
        return self._nodes

    @property
    def cached_blocks(self) -> int:
        return self._nodes

    # ---- lookup -------------------------------------------------------
    def _chunks(self, tokens) -> List[tuple]:
        bs = self.block_size
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + bs])
                for i in range(0, len(toks) - bs + 1, bs)]

    def match(self, tokens) -> Tuple[List[int], int]:
        """Longest cached prefix of ``tokens``: returns (blocks,
        matched_token_count).  Only FULL blocks match, and at least one
        token is always left for the caller to prefill (a prefill must
        see >= 1 real token to produce next-token logits), so the match
        is capped at ``len(tokens) - 1`` rounded down to a block
        boundary.  Touches the matched path's LRU clocks."""
        self.queries += 1
        usable = (len(tokens) - 1) // self.block_size
        blocks: List[int] = []
        node = self._root
        tick = next(self._clock)
        for chunk in self._chunks(tokens)[:usable]:
            child = node.children.get(chunk)
            if child is None:
                break
            child.last_used = tick
            blocks.append(child.block)
            node = child
        if blocks:
            self.hit_queries += 1
            self.hit_blocks += len(blocks)
        return blocks, len(blocks) * self.block_size

    # ---- insertion ----------------------------------------------------
    def insert(self, tokens, blocks: List[int]) -> int:
        """Register a prefilled prompt: ``blocks[i]`` holds tokens
        ``[i*bs, (i+1)*bs)``.  Existing nodes win (a concurrent
        identical prompt admitted cold keeps the FIRST copy; the
        duplicate blocks stay slot-owned and retire normally).  New
        nodes incref their block — the tree's own reference.  Returns
        the number of newly adopted blocks."""
        node = self._root
        adopted = 0
        tick = next(self._clock)
        for chunk, block in zip(self._chunks(tokens), blocks):
            child = node.children.get(chunk)
            if child is None:
                child = _Node(chunk, int(block), node)
                child.path_hash = path_fingerprint(node.path_hash, chunk)
                node.children[chunk] = child
                self._alloc.incref([int(block)])
                self._fingerprints.add(child.path_hash)
                self._nodes += 1
                adopted += 1
            child.last_used = tick
            node = child
        self.inserted_blocks += adopted
        return adopted

    # ---- eviction -----------------------------------------------------
    def _leaves(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            if n is not self._root and not n.children:
                out.append(n)
            stack.extend(n.children.values())
        return out

    def _drop(self, node: _Node) -> None:
        node.parent.children.pop(node.key, None)
        self._alloc.decref([node.block])
        self._fingerprints.discard(node.path_hash)
        self._nodes -= 1
        self.evicted_blocks += 1

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping LRU leaves
        whose block only the tree references (refcount 1 — a block a
        live slot still uses is pinned).  Dropping a leaf may expose
        its parent as the next LRU leaf, so parents are PROMOTED into
        the candidate heap as their last child falls — one tree walk
        per call, not one per freed block (eviction sits on the
        admission hot path).  Returns blocks freed."""
        import heapq
        heap = [(lf.last_used, id(lf), lf) for lf in self._leaves()]
        heapq.heapify(heap)
        freed = 0
        while heap and freed < n_blocks:
            _, _, node = heapq.heappop(heap)
            # re-check at pop time: the node must still be an attached
            # leaf (heap entries can go stale as the tree mutates) and
            # unpinned (refcounts don't change within a call, so a
            # skipped pinned leaf stays out for good)
            if node.children or \
                    node.parent.children.get(node.key) is not node:
                continue
            if self._alloc.refcount(node.block) != 1:
                continue                       # pinned by a live slot
            parent = node.parent
            self._drop(node)
            freed += 1
            if parent is not self._root and not parent.children:
                heapq.heappush(heap,
                               (parent.last_used, id(parent), parent))
        return freed

    def flush(self) -> int:
        """Drop EVERY node, releasing the tree's references (blocks a
        slot still uses survive under the slot's own reference).  The
        drain/leak accounting path."""
        dropped = 0
        stack = list(self._root.children.values())
        order: List[_Node] = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):          # leaves before parents
            self._drop(n)
            dropped += 1
        return dropped

    # ---- router-facing summary ----------------------------------------
    def summary(self) -> dict:
        """Cheap per-replica digest for cache-aware routing: the
        block-granular fingerprint set (path hashes of every cached
        chunk path — maintained incrementally, O(1) to hand out) plus
        hit/evict counters.  A router scores an incoming prompt by
        rolling :func:`path_fingerprint` over its chunks and counting
        how many consecutive hashes live in ``fingerprints`` — prefix
        overlap without ever walking this replica's tree."""
        return {
            "block_size": self.block_size,
            "fingerprints": self._fingerprints,
            "cached_blocks": self._nodes,
            "hit_queries": self.hit_queries,
            "queries": self.queries,
            "evicted_blocks": self.evicted_blocks,
        }

    # ---- stats --------------------------------------------------------
    @property
    def stats(self) -> dict:
        return {
            "prefix_queries": self.queries,
            "prefix_hit_queries": self.hit_queries,
            "prefix_hit_rate": round(self.hit_queries / self.queries, 4)
            if self.queries else 0.0,
            "prefix_hit_blocks": self.hit_blocks,
            "prefix_cached_blocks": self._nodes,
            "prefix_evicted_blocks": self.evicted_blocks,
        }
