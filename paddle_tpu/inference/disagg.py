"""Disaggregated prefill/decode serving (DistServe-style).

One engine interleaving prefill and decode has a structural tail
problem: a long prompt's prefill runs BETWEEN decode steps, so every
in-flight stream stalls for the whole prefill — decode p99 inflates
with prompt length even though decode work per tick is constant.
DistServe (Zhong et al.) splits the two phases onto separate resources:
prefill workers chew prompts at their own pace, decode engines tick
uninterrupted, and the KV handoff is the only coupling.

The paged block pool makes that handoff nearly free: a prefill WRITES
pool blocks, and handing the request to the decode engine is handing it
the block ids — no KV copy, no re-compute, just refcounted pointers
(exactly the currency the radix prefix cache already trades in).

Topology here: ``DisaggServingEngine`` wraps ONE decode
``InferenceEngine`` (paged, its admission loop bypassed) plus a
``PrefillWorker`` holding its OWN compiled prefill executables over the
same parameters and the same shared pool.  On CPU that is two executable
sets interleaved on one device — the scheduling boundary the real
deployment maps onto separate device groups (prefill mesh / decode
mesh); the handoff protocol (blocks + first-token logits) is identical
either way.  The decode engine's ``step()`` therefore NEVER runs a
prefill: its step latency is pure decode, which is the p99 the loadgen
measures.

Flow per ``step()``:

1. prefill phase: up to ``prefills_per_step`` queued requests run on
   the PrefillWorker (radix-cache match -> block alloc -> suffix
   prefill -> trim + adopt into the radix tree) and park as HANDOFF
   records (req, blocks, logits);
2. admission phase: free decode slots adopt parked handoffs — install
   the block table, sample the first token from the handed-off logits
   (``InferenceEngine.admit_handoff``);
3. decode phase: one uninterrupted decode tick (spec decoding rides
   along unchanged — the draft prefill is part of admission).
"""
from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .engine import InferenceEngine, Request
from .paged_kv import blocks_for

__all__ = ["DisaggServingEngine", "PrefillWorker"]


class PrefillWorker:
    """The prefill half: its own jitted prefill executables (the
    stand-in for a separate device group) writing into the DECODE
    engine's shared block pool / radix cache.  Single-threaded
    interleave — the wrapper alternates phases, so cache/alloc state
    is never raced."""

    def __init__(self, engine: InferenceEngine):
        if engine.kv_layout != "paged":
            raise ValueError(
                "disaggregated prefill needs kv_layout='paged' — the "
                "KV handoff travels through the block pool")
        self.engine = engine
        dargs = (1,) if engine._donate else ()
        self._cold_jit = jax.jit(engine._prefill_paged_cold_fn,
                                 donate_argnums=dargs)
        self._ext_jit = jax.jit(engine._prefill_paged_ext_fn,
                                donate_argnums=dargs)
        self.prefills = 0

    def warmup(self, buckets: Optional[List[int]] = None):
        """Compile the worker's executables per bucket (transient pool
        blocks, same throwaway discipline as engine.warmup)."""
        eng = self.engine
        for b in (buckets or eng.buckets):
            n = blocks_for(b, eng.block_size)
            if n > eng._alloc.capacity:
                continue
            blocks = eng._alloc.alloc(n)
            assert blocks is not None, "warmup needs an empty pool"
            row = np.zeros(eng.blocks_per_slot, np.int32)
            row[:n] = blocks
            ids = jnp.zeros((1, b), jnp.int32)
            _, cache = eng._timed_exec(
                "prefill_ms", ("disagg", b), self._cold_jit,
                eng.params, eng.cache, ids, jnp.asarray(row),
                np.int32(1))
            eng.cache = cache
            if eng._prefix is not None:
                _, cache = eng._timed_exec(
                    "prefill_ms", ("disagg_ext", b), self._ext_jit,
                    eng.params, eng.cache, ids, jnp.asarray(row),
                    np.int32(0), np.int32(1))
                eng.cache = cache
            eng._alloc.decref(blocks)
        return self

    def try_prefill(self, req: Request):
        """Run one request's prefill; returns the handoff record
        ``(req, blocks, logits)`` or None when the pool cannot hold it
        yet (caller leaves it queued — head-of-line FIFO, same policy
        as engine admission).  The match/alloc/shed/trim/adopt sequence
        is ``engine._paged_prefill`` — ONE implementation shared with
        in-engine admission, run here on the WORKER's executables."""
        rec = self.engine._paged_prefill(req, self._cold_jit,
                                         self._ext_jit, "disagg")
        if rec is None:
            return None
        blocks, _plen, logits = rec
        self.prefills += 1
        return req, blocks, logits


class DisaggServingEngine:
    """Prefill/decode-disaggregated serving: duck-types the
    ``InferenceEngine`` driving surface (add_request / step /
    step_or_raise / has_work / run / drain / results / stats), so the
    load harness and router treat it as just another replica."""

    def __init__(self, model, prefills_per_step: int = 1,
                 handoff_depth: int = 4, **engine_kw):
        engine_kw.setdefault("kv_layout", "paged")
        self.decode = InferenceEngine(model, **engine_kw)
        self.worker = PrefillWorker(self.decode)
        self.prefills_per_step = int(prefills_per_step)
        self.handoff_depth = int(handoff_depth)
        self._queue: deque = deque()
        self._handoffs: deque = deque()
        self.handoffs_total = 0
        # telemetry: the disaggregation-specific counters ride the same
        # registry as the wrapped engine's serve_* metrics
        from ..observability import metrics as _metrics
        lbl = dict(engine=self.decode.telemetry_label)
        self._m_handoffs = _metrics.counter(
            "disagg_handoffs_total", "prefill->decode KV handoffs",
            labels=("engine",)).labels(**lbl)
        self._m_handoff_q = _metrics.gauge(
            "disagg_handoff_queue", "parked handoff records",
            labels=("engine",)).labels(**lbl)

    # ---- delegated surface --------------------------------------------
    @property
    def model(self):
        return self.decode.model

    @property
    def results(self) -> Dict[int, np.ndarray]:
        return self.decode.results

    @property
    def request_stats(self) -> Dict[int, dict]:
        return self.decode.request_stats

    @property
    def _timings(self):
        return self.decode._timings

    @property
    def _prefix(self):
        return self.decode._prefix

    @property
    def kv_layout(self):
        return self.decode.kv_layout

    @property
    def batch_slots(self):
        return self.decode.batch_slots

    @property
    def num_active(self) -> int:
        return self.decode.num_active

    @property
    def blocks_in_use(self):
        return self.decode.blocks_in_use

    @property
    def telemetry_label(self) -> str:
        return self.decode.telemetry_label

    def prefix_summary(self):
        return self.decode.prefix_summary()

    def warmup(self, buckets: Optional[List[int]] = None):
        self.decode.warmup(buckets)
        self.worker.warmup(buckets or self.decode.buckets)
        return self

    def add_request(self, prompt, **kw) -> int:
        """Queue on the WRAPPER (the decode engine's own queue stays
        empty — its admission loop never runs a prefill).  Validation
        rides the engine's add_request, then the request is lifted out."""
        rid = self.decode.add_request(prompt, **kw)
        req = self.decode._queue.pop()
        self._queue.append(req)
        return rid

    # ---- the disaggregated step ---------------------------------------
    def _reclaim_preempted(self):
        """A decode-side preemption parks its victim on the DECODE
        engine's queue; pull it back so its resume prefill runs on the
        worker, keeping the decode path prefill-free."""
        if self.decode._queue:
            self._queue = deque(list(self.decode._queue) +
                                list(self._queue))
            self.decode._queue.clear()

    def _expire_queued(self):
        now = time.perf_counter()
        for r in [r for r in self._queue
                  if r.deadline is not None and now >= r.deadline]:
            self._queue.remove(r)
            self.decode.expire_queued_request(r, now)

    def step(self) -> int:
        """One disaggregated round: prefill phase -> handoff admission
        -> ONE pure decode tick."""
        produced = 0
        self._reclaim_preempted()
        self._expire_queued()
        # 1) prefill phase (bounded: parked handoffs hold pool blocks)
        done = 0
        while (self._queue and done < self.prefills_per_step
               and len(self._handoffs) < self.handoff_depth
               and self.decode._admitting):
            rec = self.worker.try_prefill(self._queue[0])
            if rec is None:
                break                     # pool full; head-of-line waits
            self._queue.popleft()
            self._handoffs.append(rec)
            self.handoffs_total += 1
            self._m_handoffs.inc()
            done += 1
        self._m_handoff_q.set(len(self._handoffs))
        # 2) admission: free slots adopt parked handoffs
        for slot in range(self.decode.batch_slots):
            if not self._handoffs or not self.decode._admitting:
                break
            if self.decode._slots[slot] is None:
                req, blocks, logits = self._handoffs.popleft()
                self.decode.admit_handoff(req, slot, blocks, logits)
                produced += 1
        # 3) pure decode tick
        produced += self.decode.step()
        return produced

    def step_or_raise(self) -> int:
        produced = self.step()
        if (produced == 0 and self.decode.num_active == 0
                and not self._handoffs and self._queue
                and self.decode._admitting):
            raise RuntimeError(
                "admission stalled: queued requests but the prefill "
                "worker cannot place them and nothing active to retire")
        return produced

    @property
    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._handoffs)
                or self.decode.has_work)

    def run(self) -> Dict[int, np.ndarray]:
        while self.has_work:
            self.step_or_raise()
        return self.decode.results

    def generate(self, prompt, **kw) -> np.ndarray:
        rid = self.add_request(prompt, **kw)
        while rid not in self.decode.results:
            self.step_or_raise()
        return self.decode.results[rid]

    def _release_handoffs(self) -> List[Request]:
        """Return parked handoffs' blocks to the pool and their
        requests to the caller (drain path)."""
        out = []
        while self._handoffs:
            req, blocks, _ = self._handoffs.popleft()
            self.decode._alloc.decref(blocks)
            out.append(req)
        return out

    def drain(self, timeout_s: Optional[float] = None) -> List[Request]:
        leftover = list(self._queue)
        self._queue.clear()
        leftover = self._release_handoffs() + leftover
        leftover = self.decode.drain(timeout_s) + leftover
        return leftover

    def check_leak_free(self):
        assert not self._handoffs, \
            "leak check requires drained handoffs"
        self.decode.check_leak_free()

    @property
    def stats(self) -> dict:
        s = self.decode.stats
        s["disaggregated"] = True
        s["prefill_worker_prefills"] = self.worker.prefills
        s["handoffs"] = self.handoffs_total
        s["handoff_queue"] = len(self._handoffs)
        return s
