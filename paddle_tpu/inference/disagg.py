"""Disaggregated prefill/decode serving (DistServe-style).

One engine interleaving prefill and decode has a structural tail
problem: a long prompt's prefill runs BETWEEN decode steps, so every
in-flight stream stalls for the whole prefill — decode p99 inflates
with prompt length even though decode work per tick is constant.
DistServe (Zhong et al.) splits the two phases onto separate resources:
prefill workers chew prompts at their own pace, decode engines tick
uninterrupted, and the KV handoff is the only coupling.

The paged block pool makes that handoff nearly free: a prefill WRITES
pool blocks, and handing the request to the decode engine is handing it
the block ids — no KV copy, no re-compute, just refcounted pointers
(exactly the currency the radix prefix cache already trades in).

Topology, two rungs:

* ``DisaggServingEngine(model)`` — SHARED-POOL disaggregation: the
  ``PrefillWorker`` holds its own compiled prefill executables over the
  same parameters and the same pool, interleaved on one device group.
  The scheduling boundary is real (the decode engine's ``step()`` never
  runs a prefill), the device boundary is not.
* ``DisaggServingEngine(model, prefill_devices=k)`` — DISJOINT device
  groups (ISSUE 18): the process device list is carved into a prefill
  group (first ``k`` devices) and a decode group (the rest), each with
  its own ``{"dp": 1, "tp": group}`` mesh.  The worker owns a SEPARATE
  copy of the parameters and a SEPARATE block pool / allocator / radix
  cache committed to the prefill mesh; the decode engine compiles
  against the decode mesh.  The KV handoff becomes a device-to-device
  block transfer: a fixed-shape gather on the prefill group, a resharding
  ``device_put`` across the group boundary, and a fixed-shape scatter
  into the decode group's pool (both executables compile once — the
  block-id rows are padded to ``blocks_per_slot``, padding rows travel
  through null block 0).

Flow per ``step()``:

1. prefill phase: up to ``prefills_per_step`` queued requests run on
   the PrefillWorker (radix-cache match -> block alloc -> suffix
   prefill -> trim + adopt into the radix tree) and park as HANDOFF
   records (req, blocks, logits);
2. admission phase: free decode slots adopt parked handoffs — under
   disjoint groups the blocks are first transferred into the decode
   pool — and sample the first token from the handed-off logits
   (``InferenceEngine.admit_handoff``);
3. decode phase: one uninterrupted decode tick (spec decoding rides
   along unchanged — the draft prefill is part of admission).
"""
from __future__ import annotations

import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .engine import InferenceEngine, Request
from .paged_kv import BlockAllocator, blocks_for, init_paged_cache
from .prefix_cache import RadixPrefixCache

__all__ = ["DisaggServingEngine", "PrefillWorker"]


class PrefillWorker:
    """The prefill half: its own jitted prefill executables writing
    either into the DECODE engine's shared pool (``mesh is None`` /
    the engine's own mesh) or — disjoint disaggregation — into its OWN
    pool committed to its own device-group mesh.  Either way the state
    ``domain`` (params / cache / allocator / radix cache) this worker
    exposes is what ``engine._paged_prefill`` runs against.
    Single-threaded interleave — the wrapper alternates phases, so
    cache/alloc state is never raced."""

    def __init__(self, engine: InferenceEngine, mesh=None):
        if engine.kv_layout != "paged":
            raise ValueError(
                "disaggregated prefill needs kv_layout='paged' — the "
                "KV handoff travels through the block pool")
        self.engine = engine
        self._own = mesh is not None and mesh is not engine.mesh
        self.mesh = mesh if mesh is not None else engine.mesh
        if self._own:
            # DistServe for real: a second copy of the weights and a
            # second pool, committed to the PREFILL group's mesh.  The
            # block handoff is now the only coupling to the decode side.
            try:
                self._params = engine._shard_params_over(
                    self.mesh, engine.params, engine.model)
            except Exception as e:  # pragma: no cover - degrade path
                engine._shard_failed("disagg_prefill_params", e)
                self._params = engine.params
            pool = init_paged_cache(engine.model, engine.num_blocks + 1,
                                    engine.block_size,
                                    engine._cache_dtype,
                                    kv_dtype=engine.kv_dtype)
            try:
                self._cache = engine._shard_paged_cache_arrays(
                    self.mesh, pool)
            except Exception as e:  # pragma: no cover - degrade path
                engine._shard_failed("disagg_prefill_pool", e)
                self._cache = pool
            self._own_alloc = BlockAllocator(engine.num_blocks + 1,
                                             engine.block_size)
            self._own_prefix = RadixPrefixCache(
                self._own_alloc, engine.block_size) \
                if engine._prefix is not None else None
        dargs = (1,) if engine._donate else ()
        cold_fn = engine._prefill_paged_cold_fn
        ext_fn = engine._prefill_paged_ext_fn
        if self._own:
            # distinct function identities: bound methods hash equal
            # across attribute accesses, so jax's trace cache would
            # otherwise REUSE the decode engine's traced jaxpr — fatal
            # once the MoE serve-ep dispatch bakes its concrete mesh
            # into a shard_map (the worker's group is a different
            # device set).  functools.partial hashes by identity, so
            # each wrapper traces under ITS mesh guard.
            import functools
            cold_fn = functools.partial(cold_fn)
            ext_fn = functools.partial(ext_fn)
        self._cold_jit = jax.jit(cold_fn, donate_argnums=dargs)
        self._ext_jit = jax.jit(ext_fn, donate_argnums=dargs)
        self.prefills = 0

    # ---- the state domain _paged_prefill runs against -----------------
    @property
    def params(self):
        return self._params if self._own else self.engine.params

    @property
    def cache(self):
        return self._cache if self._own else self.engine.cache

    @cache.setter
    def cache(self, value):
        if self._own:
            self._cache = value
        else:
            self.engine.cache = value

    @property
    def _alloc(self):
        return self._own_alloc if self._own else self.engine._alloc

    @property
    def _prefix(self):
        return self._own_prefix if self._own else self.engine._prefix

    def _alloc_blocks(self, n: int) -> Optional[List[int]]:
        if not self._own:
            return self.engine._alloc_blocks(n)
        if n <= 0:
            return []
        out = self._alloc.alloc(n)
        if out is None and self._prefix is not None:
            self._prefix.evict(n - self._alloc.num_free)
            out = self._alloc.alloc(n)
        return out

    def warmup(self, buckets: Optional[List[int]] = None):
        """Compile the worker's executables per bucket (transient pool
        blocks, same throwaway discipline as engine.warmup)."""
        eng = self.engine
        for b in (buckets or eng.buckets):
            n = blocks_for(b, eng.block_size)
            if n > self._alloc.capacity:
                continue
            blocks = self._alloc.alloc(n)
            assert blocks is not None, "warmup needs an empty pool"
            row = np.zeros(eng.blocks_per_slot, np.int32)
            row[:n] = blocks
            ids = jnp.zeros((1, b), jnp.int32)
            _, cache, _ = eng._timed_exec(
                "prefill_ms", ("disagg", b), self._cold_jit,
                self.params, self.cache, ids, jnp.asarray(row),
                np.int32(1), mesh=self.mesh)
            self.cache = cache
            if self._prefix is not None:
                _, cache, _ = eng._timed_exec(
                    "prefill_ms", ("disagg_ext", b), self._ext_jit,
                    self.params, self.cache, ids, jnp.asarray(row),
                    np.int32(0), np.int32(1), mesh=self.mesh)
                self.cache = cache
            self._alloc.decref(blocks)
        return self

    def try_prefill(self, req: Request):
        """Run one request's prefill; returns the handoff record
        ``(req, blocks, logits)`` — block ids in THIS worker's pool —
        or None when the pool cannot hold it yet (caller leaves it
        queued — head-of-line FIFO, same policy as engine admission).
        The match/alloc/shed/trim/adopt sequence is
        ``engine._paged_prefill`` — ONE implementation shared with
        in-engine admission, run here on the WORKER's executables over
        the WORKER's state domain."""
        rec = self.engine._paged_prefill(req, self._cold_jit,
                                         self._ext_jit, "disagg",
                                         domain=self)
        if rec is None:
            return None
        blocks, _plen, logits = rec
        self.prefills += 1
        return req, blocks, logits


class DisaggServingEngine:
    """Prefill/decode-disaggregated serving: duck-types the
    ``InferenceEngine`` driving surface (add_request / step /
    step_or_raise / has_work / run / drain / results / stats), so the
    load harness and router treat it as just another replica.

    ``prefill_devices=k`` (ISSUE 18) carves the process device list
    into REAL disjoint groups: devices ``[0, k)`` become the prefill
    mesh, the rest the decode mesh; the KV handoff then crosses the
    group boundary as a gather -> resharding device_put -> scatter
    block transfer.  ``prefill_tp``/``decode_tp`` override each
    group's tensor-parallel degree (default: the full group);
    ``prefill_ep``/``decode_ep`` (ISSUE 19) grow each group's mesh an
    'ep' axis for MoE expert parallelism — expert FFN weights shard
    over it per group and the MoE serving dispatch routes through the
    fixed-shape capacity a2a on that group's devices.  Defaults come
    from ``PADDLE_TPU_SERVE_EP`` so one env knob configures both the
    monolithic and the disaggregated topology."""

    def __init__(self, model, prefills_per_step: int = 1,
                 handoff_depth: int = 4, prefill_devices: int = 0,
                 prefill_tp: Optional[int] = None,
                 decode_tp: Optional[int] = None,
                 prefill_ep: Optional[int] = None,
                 decode_ep: Optional[int] = None, **engine_kw):
        engine_kw.setdefault("kv_layout", "paged")
        self._disjoint = int(prefill_devices) > 0
        prefill_mesh = None
        if self._disjoint:
            if engine_kw.get("mesh") is not None:
                raise ValueError(
                    "prefill_devices carves its own meshes — pass "
                    "either it or mesh=, not both")
            from ..distributed.mesh import create_mesh
            devs = list(jax.devices())
            k = int(prefill_devices)
            if k >= len(devs):
                raise ValueError(
                    f"prefill_devices={k} leaves no decode group "
                    f"(process has {len(devs)} devices)")
            n_dec = len(devs) - k
            env_ep = os.environ.get("PADDLE_TPU_SERVE_EP", "").strip()
            p_ep = int(prefill_ep if prefill_ep is not None
                       else (env_ep or 1))
            d_ep = int(decode_ep if decode_ep is not None
                       else (env_ep or 1))
            for nm, grp, ep in (("prefill", k, p_ep),
                                ("decode", n_dec, d_ep)):
                if ep < 1 or grp % ep != 0:
                    raise ValueError(
                        f"{nm}_ep={ep} does not divide the {nm} "
                        f"group ({grp} devices)")
            p_tp = int(prefill_tp or (k // p_ep))
            d_tp = int(decode_tp or (n_dec // d_ep))

            def _axes(n, tp, ep):
                axes = {"dp": n // (tp * ep), "tp": tp}
                if ep > 1:
                    axes["ep"] = ep
                return axes

            prefill_mesh = create_mesh(_axes(k, p_tp, p_ep),
                                       devices=devs[:k])
            engine_kw["mesh"] = create_mesh(_axes(n_dec, d_tp, d_ep),
                                            devices=devs[k:])
        self.decode = InferenceEngine(model, **engine_kw)
        self.worker = PrefillWorker(self.decode, mesh=prefill_mesh)
        self.prefills_per_step = int(prefills_per_step)
        self.handoff_depth = int(handoff_depth)
        self._queue: deque = deque()
        self._handoffs: deque = deque()
        self.handoffs_total = 0
        self.transfers = 0
        if self._disjoint:
            dargs = (0,) if self.decode._donate else ()
            self._gather_jit = jax.jit(self._handoff_gather_fn)
            self._scatter_jit = jax.jit(self._handoff_scatter_fn,
                                        donate_argnums=dargs)
        # telemetry: the disaggregation-specific counters ride the same
        # registry as the wrapped engine's serve_* metrics
        from ..observability import metrics as _metrics
        lbl = dict(engine=self.decode.telemetry_label)
        self._m_handoffs = _metrics.counter(
            "disagg_handoffs_total", "prefill->decode KV handoffs",
            labels=("engine",)).labels(**lbl)
        self._m_handoff_q = _metrics.gauge(
            "disagg_handoff_queue", "parked handoff records",
            labels=("engine",)).labels(**lbl)

    # ---- delegated surface --------------------------------------------
    @property
    def model(self):
        return self.decode.model

    @property
    def results(self) -> Dict[int, np.ndarray]:
        return self.decode.results

    @property
    def request_stats(self) -> Dict[int, dict]:
        return self.decode.request_stats

    @property
    def _timings(self):
        return self.decode._timings

    @property
    def _moe_load(self):
        # worker prefills accumulate into the DECODE engine's expert
        # counters (engine._accum_moe) — one combined histogram
        return self.decode._moe_load

    @property
    def _prefix(self):
        return self.worker._prefix

    @property
    def kv_layout(self):
        return self.decode.kv_layout

    @property
    def batch_slots(self):
        return self.decode.batch_slots

    @property
    def num_active(self) -> int:
        return self.decode.num_active

    @property
    def blocks_in_use(self):
        return self.decode.blocks_in_use

    @property
    def telemetry_label(self) -> str:
        return self.decode.telemetry_label

    def prefix_summary(self):
        return self.decode.prefix_summary()

    def warmup(self, buckets: Optional[List[int]] = None):
        self.decode.warmup(buckets)
        self.worker.warmup(buckets or self.decode.buckets)
        return self

    def add_request(self, prompt, **kw) -> int:
        """Queue on the WRAPPER (the decode engine's own queue stays
        empty — its admission loop never runs a prefill).  Validation
        rides the engine's add_request, then the request is lifted out."""
        rid = self.decode.add_request(prompt, **kw)
        req = self.decode._queue.pop()
        self._queue.append(req)
        return rid

    # ---- cross-group block transfer (disjoint mode) -------------------
    def _handoff_gather_fn(self, cache, row):
        """Fixed-shape gather of a slot's block rows out of the PREFILL
        pool: row is the ``blocks_per_slot``-padded block-id vector
        (padding = null block 0, whose garbage never gets read)."""
        out = [cache.k[:, row], cache.v[:, row]]
        if cache.k_scale is not None:
            out += [cache.k_scale[:, row], cache.v_scale[:, row]]
        return tuple(out)

    def _handoff_scatter_fn(self, cache, row, *rows):
        """Fixed-shape scatter of transferred block rows into the
        DECODE pool at freshly-allocated ids (padding rows land in null
        block 0 — harmless by construction)."""
        k = cache.k.at[:, row].set(rows[0])
        v = cache.v.at[:, row].set(rows[1])
        if len(rows) == 4:
            return type(cache)(k, v,
                               cache.k_scale.at[:, row].set(rows[2]),
                               cache.v_scale.at[:, row].set(rows[3]))
        return type(cache)(k, v)

    def _transfer_handoff(self, blocks) -> Optional[List[int]]:
        """Device-to-device KV handoff: gather the blocks on the
        prefill group, reshard across the group boundary, scatter into
        the decode pool.  Returns the DECODE pool block ids (slot
        refcounts taken) or None when the decode pool is full."""
        eng = self.decode
        dst = eng._alloc_blocks(len(blocks))
        if dst is None:
            return None
        row_src = np.zeros(eng.blocks_per_slot, np.int32)
        row_src[:len(blocks)] = blocks
        row_dst = np.zeros(eng.blocks_per_slot, np.int32)
        row_dst[:len(dst)] = dst
        rows = eng._timed_exec(
            "prefill_ms", ("handoff_gather", 0), self._gather_jit,
            self.worker.cache, jnp.asarray(row_src),
            mesh=self.worker.mesh)
        # the group boundary: recommit each gathered stack to the
        # decode group's pool sharding (this is the actual D2D copy)
        dims = [(None, None, None, "tp", None)] * 2 + \
            [(None, None, None, "tp")] * (len(rows) - 2)
        moved = tuple(eng._put(eng.mesh, r, d)
                      for r, d in zip(rows, dims))
        eng.cache = eng._timed_exec(
            "prefill_ms", ("handoff_scatter", 0), self._scatter_jit,
            eng.cache, jnp.asarray(row_dst), *moved)
        self.transfers += 1
        return dst

    # ---- the disaggregated step ---------------------------------------
    def _reclaim_preempted(self):
        """A decode-side preemption parks its victim on the DECODE
        engine's queue; pull it back so its resume prefill runs on the
        worker, keeping the decode path prefill-free."""
        if self.decode._queue:
            self._queue = deque(list(self.decode._queue) +
                                list(self._queue))
            self.decode._queue.clear()

    def _expire_queued(self):
        now = time.perf_counter()
        for r in [r for r in self._queue
                  if r.deadline is not None and now >= r.deadline]:
            self._queue.remove(r)
            self.decode.expire_queued_request(r, now)

    def step(self) -> int:
        """One disaggregated round: prefill phase -> handoff admission
        -> ONE pure decode tick."""
        produced = 0
        self._reclaim_preempted()
        self._expire_queued()
        # 1) prefill phase (bounded: parked handoffs hold pool blocks)
        done = 0
        while (self._queue and done < self.prefills_per_step
               and len(self._handoffs) < self.handoff_depth
               and self.decode._admitting):
            rec = self.worker.try_prefill(self._queue[0])
            if rec is None:
                break                     # pool full; head-of-line waits
            self._queue.popleft()
            self._handoffs.append(rec)
            self.handoffs_total += 1
            self._m_handoffs.inc()
            done += 1
        self._m_handoff_q.set(len(self._handoffs))
        # 2) admission: free slots adopt parked handoffs (crossing the
        #    device-group boundary first under disjoint disaggregation)
        for slot in range(self.decode.batch_slots):
            if not self._handoffs or not self.decode._admitting:
                break
            if self.decode._slots[slot] is None:
                req, blocks, logits = self._handoffs[0]
                if self._disjoint:
                    dst = self._transfer_handoff(blocks)
                    if dst is None:
                        break    # decode pool full; stays parked
                    self.worker._alloc.decref(blocks)
                    blocks = dst
                    logits = np.asarray(jax.device_get(logits))
                self._handoffs.popleft()
                self.decode.admit_handoff(req, slot, blocks, logits)
                produced += 1
        # 3) pure decode tick
        produced += self.decode.step()
        return produced

    def step_or_raise(self) -> int:
        produced = self.step()
        if (produced == 0 and self.decode.num_active == 0
                and not self._handoffs and self._queue
                and self.decode._admitting):
            raise RuntimeError(
                "admission stalled: queued requests but the prefill "
                "worker cannot place them and nothing active to retire")
        return produced

    @property
    def has_work(self) -> bool:
        return (bool(self._queue) or bool(self._handoffs)
                or self.decode.has_work)

    def run(self) -> Dict[int, np.ndarray]:
        while self.has_work:
            self.step_or_raise()
        return self.decode.results

    def generate(self, prompt, **kw) -> np.ndarray:
        rid = self.add_request(prompt, **kw)
        while rid not in self.decode.results:
            self.step_or_raise()
        return self.decode.results[rid]

    def _release_handoffs(self) -> List[Request]:
        """Return parked handoffs' blocks to the pool they live in
        (the WORKER's domain) and their requests to the caller (drain
        path)."""
        out = []
        while self._handoffs:
            req, blocks, _ = self._handoffs.popleft()
            self.worker._alloc.decref(blocks)
            out.append(req)
        return out

    def drain(self, timeout_s: Optional[float] = None) -> List[Request]:
        leftover = list(self._queue)
        self._queue.clear()
        leftover = self._release_handoffs() + leftover
        leftover = self.decode.drain(timeout_s) + leftover
        return leftover

    def check_leak_free(self):
        assert not self._handoffs, \
            "leak check requires drained handoffs"
        self.decode.check_leak_free()
        if self.worker._own:
            if self.worker._prefix is not None:
                self.worker._prefix.flush()
            self.worker._alloc.check_leak_free()

    @property
    def stats(self) -> dict:
        s = self.decode.stats
        s["disaggregated"] = True
        s["prefill_worker_prefills"] = self.worker.prefills
        s["handoffs"] = self.handoffs_total
        s["handoff_queue"] = len(self._handoffs)
        s["disjoint_groups"] = self._disjoint
        if self._disjoint:
            s["handoff_transfers"] = self.transfers
            s["prefill_mesh"] = {
                str(ax): int(n)
                for ax, n in self.worker.mesh.shape.items()}
            s["prefill_devices"] = [
                int(d.id)
                for d in np.asarray(self.worker.mesh.devices).flat]
            s["decode_devices"] = [
                int(d.id)
                for d in np.asarray(self.decode.mesh.devices).flat]
        return s
