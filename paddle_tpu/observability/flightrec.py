"""Flight recorder: an always-on black box for crashed/killed runs.

PR 13 made telemetry live — metrics, spans, SLO verdicts — but all of it
dies with the process: a hung decode tick or a preempted trainer takes
its spans down with it, and the post-mortem is a shrug.  The reference
framework keeps its profiler + error machinery at the PLATFORM layer,
beside the device runtime (PAPER.md §1 layer 0), precisely so failure
artifacts outlive the failing op.  This module is that posture for the
host process:

- **Ring**: a bounded deque of the most recent step/tick telemetry
  snapshots (trainer steps, decode ticks — kind + wall time + the
  counters the caller already has on host).  Recording is ``deque
  .append`` of a small dict: no host syncs, no jax calls, O(ring) memory
  forever (``PADDLE_TPU_FLIGHTREC_RING``, default 256 entries).
- **Events**: a second bounded deque of notable instants — checkpoint
  saves/restores, XLA compiles, anomaly rollbacks, preemptions,
  injected faults — each stamped on the span-tracer clock so the ring
  and the span buffer align.
- **Dump**: ``dump(reason)`` writes an ATOMIC post-mortem bundle — a
  directory staged as ``.tmp`` and renamed (the checkpoint-commit
  idiom: a crash mid-dump never leaves a half bundle that parses) —
  holding ``bundle.json`` (reason, ring, events, metrics snapshot,
  all-thread stacks) and ``trace.json`` (a Chrome-trace document: the
  span buffer tail plus the ring synthesized as spans, so the timeline
  renders even when the tracer was never armed).

Dump triggers (wired through the entry points):

- unhandled exception ending the process (``install()`` chains
  ``sys.excepthook`` / ``threading.excepthook``);
- SIGTERM/SIGINT riding ``resilience.PreemptionGuard``;
- ``anomaly_policy='rollback'`` firing in ``SpmdTrainer``;
- fault-harness kills (``PADDLE_FAULT_CKPT_TRUNCATE`` hard-exit,
  worker kills, ``PADDLE_FAULT_SIGTERM_STEP``);
- watchdog-detected stalls (observability.watchdog).

Knobs: ``PADDLE_TPU_FLIGHTREC=0`` disables recording AND dumping;
``PADDLE_TPU_FLIGHTREC=<dir>`` (or ``PADDLE_TPU_FLIGHTREC_DIR``) names
the dump directory (default ``$TMPDIR/paddle_tpu_flightrec``).  Dumps
per process are capped (``_MAX_DUMPS``) so a pathological rollback loop
cannot fill a disk with bundles.
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
import traceback
from collections import deque
from typing import Dict, List, Optional

from . import metrics as _metrics
from . import spans as _spans

__all__ = ["FlightRecorder", "recorder", "record", "note_event", "dump",
           "install", "enabled", "dump_dir", "load_bundle", "gc_bundles",
           "PID_FLIGHTREC"]

# chrome-trace process id for ring-synthesized spans (1=host, 2=requests)
PID_FLIGHTREC = 3

_RING_DEFAULT = 256
_EVENTS_DEFAULT = 64
_SPAN_TAIL_DEFAULT = 2048
_MAX_DUMPS = 16


def enabled() -> bool:
    return os.environ.get("PADDLE_TPU_FLIGHTREC", "1") != "0"


def dump_dir() -> str:
    """Where bundles land: PADDLE_TPU_FLIGHTREC_DIR wins, then a
    path-valued PADDLE_TPU_FLIGHTREC, then the tmp default."""
    d = os.environ.get("PADDLE_TPU_FLIGHTREC_DIR", "").strip()
    if d:
        return d
    env = os.environ.get("PADDLE_TPU_FLIGHTREC", "").strip()
    if env not in ("", "0", "1"):
        return env
    return os.path.join(tempfile.gettempdir(), "paddle_tpu_flightrec")


def all_thread_stacks() -> Dict[str, List[str]]:
    """{thread name (id): formatted frames} for every live thread — the
    watchdog's stall evidence and every bundle's 'where was everyone'
    page.  Pure interpreter introspection, safe from any thread."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for tid, frame in sys._current_frames().items():
        label = f"{names.get(tid, 'unknown')} ({tid})"
        out[label] = traceback.format_stack(frame)
    return out


class FlightRecorder:
    """Process-wide bounded telemetry ring + post-mortem dumper.  One
    instance (``recorder()``); tests may build private ones."""

    def __init__(self, ring: Optional[int] = None,
                 events: int = _EVENTS_DEFAULT,
                 span_tail: int = _SPAN_TAIL_DEFAULT):
        if ring is None:
            try:
                ring = int(os.environ.get("PADDLE_TPU_FLIGHTREC_RING",
                                          _RING_DEFAULT))
            except ValueError:
                ring = _RING_DEFAULT
        self.ring: deque = deque(maxlen=max(int(ring), 1))
        self.events: deque = deque(maxlen=max(int(events), 1))
        self.span_tail = int(span_tail)
        self.dumps = 0
        self.last_dump_path: Optional[str] = None
        self._seq = 0
        # RLock: a SIGTERM handler dumps too, and the signal can land
        # on the main thread while it is INSIDE another dump's critical
        # section — a plain Lock would self-deadlock the handler.  The
        # section only increments counters, so re-entry is harmless.
        self._dump_lock = threading.RLock()
        self._m_dumps = _metrics.counter(
            "flightrec_dumps_total", "post-mortem bundles written",
            labels=("reason",))

    # ---- recording (hot path: dict build + deque append) --------------
    def record(self, kind: str, dur_ms: Optional[float] = None,
               **payload):
        """One step/tick snapshot into the ring.  ``dur_ms`` lets the
        dump synthesize a timeline span for the entry; payload must be
        JSON-safe host scalars (the callers only have those)."""
        now = _spans.tracer().now_us()
        d = (dur_ms or 0.0) * 1e3
        entry = {"kind": kind, "ts_us": round(now - d, 3),
                 "dur_us": round(d, 3)}
        entry.update(payload)
        self.ring.append(entry)        # deque.append is GIL-atomic

    def note_event(self, kind: str, **info):
        """One notable instant (checkpoint, compile, rollback, fault,
        preemption) into the bounded event log."""
        ev = {"kind": kind, "ts_us": round(_spans.tracer().now_us(), 3),
              "wall": time.time()}
        ev.update(info)
        self.events.append(ev)

    # ---- bundle -------------------------------------------------------
    def bundle(self, reason: str, extra: Optional[dict] = None) -> dict:
        """The post-mortem document (JSON-safe)."""
        doc = {
            "format": "paddle_tpu.flightrec.v1",
            "reason": reason,
            "wall_time": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "ring": list(self.ring),
            "events": list(self.events),
            "stacks": all_thread_stacks(),
            "metrics": _metrics.snapshot(),
        }
        try:
            # the executable observatory rides every post-mortem: which
            # executables existed, their timings and (if analyzed)
            # roofline positions — pure dict reads, no compiles here
            from . import exec_registry as _er
            doc["executables"] = _er.snapshot()
            doc["hbm"] = _er.ledger().snapshot()
        except Exception:
            pass
        if extra:
            doc.update(extra)
        return doc

    def chrome_trace(self) -> dict:
        """Chrome-trace doc for the bundle: the live span buffer's tail
        plus the ring synthesized as 'X' spans on the flightrec track —
        a loadable timeline even when PADDLE_TPU_SPANS was never on."""
        tr = _spans.tracer()
        doc = tr.chrome_trace()
        events = doc["traceEvents"]
        # keep metadata records, bound the payload tail
        meta = [e for e in events if e.get("ph") == "M"]
        tail = [e for e in events if e.get("ph") != "M"][-self.span_tail:]
        meta.append({"name": "process_name", "ph": "M",
                     "pid": PID_FLIGHTREC, "tid": 0,
                     "args": {"name": "flight recorder"}})
        ring_spans = []
        for e in self.ring:
            ring_spans.append({
                "name": e["kind"], "ph": "X", "ts": max(e["ts_us"], 0.0),
                "dur": max(e["dur_us"], 0.0), "pid": PID_FLIGHTREC,
                "tid": 1, "cat": "flightrec",
                "args": {k: v for k, v in e.items()
                         if k not in ("kind", "ts_us", "dur_us")},
            })
        doc["traceEvents"] = meta + tail + ring_spans
        return doc

    def dump(self, reason: str, directory: Optional[str] = None,
             extra: Optional[dict] = None) -> Optional[str]:
        """Write one atomic bundle dir; returns its path (None when the
        recorder is disabled or the per-process dump cap is hit).
        Never raises — a broken dump path must not mask the failure
        being recorded."""
        if not enabled():
            return None
        with self._dump_lock:
            if self.dumps >= _MAX_DUMPS:
                return None
            self.dumps += 1
            self._seq += 1
            seq = self._seq
        try:
            base = directory or dump_dir()
            name = f"flightrec-{os.getpid()}-{seq:03d}-{reason}"
            final = os.path.join(base, name)
            tmp = final + ".tmp"
            os.makedirs(tmp, exist_ok=True)
            with open(os.path.join(tmp, "bundle.json"), "w") as f:
                json.dump(self.bundle(reason, extra=extra), f,
                          default=str)
                f.flush()
                os.fsync(f.fileno())
            with open(os.path.join(tmp, "trace.json"), "w") as f:
                json.dump(self.chrome_trace(), f)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(final):     # same pid+seq cannot collide;
                return None               # paranoia over clobbering
            os.rename(tmp, final)
            self.last_dump_path = final
            self._m_dumps.labels(reason=reason).inc()
            gc_bundles(base)
            print(f"flightrec: wrote post-mortem bundle {final} "
                  f"(reason={reason})", file=sys.stderr, flush=True)
            return final
        except Exception as e:  # pragma: no cover - dump path broken
            print(f"flightrec: bundle dump failed "
                  f"({type(e).__name__}: {e})", file=sys.stderr,
                  flush=True)
            return None


_RECORDER = FlightRecorder()


def recorder() -> FlightRecorder:
    return _RECORDER


def record(kind: str, dur_ms: Optional[float] = None, **payload):
    """Module-level ring record (the entry points' one-liner).  A
    disabled recorder (PADDLE_TPU_FLIGHTREC=0) costs one env read."""
    if enabled():
        _RECORDER.record(kind, dur_ms=dur_ms, **payload)


def note_event(kind: str, **info):
    if enabled():
        _RECORDER.note_event(kind, **info)


def dump(reason: str, directory: Optional[str] = None,
         extra: Optional[dict] = None) -> Optional[str]:
    return _RECORDER.dump(reason, directory=directory, extra=extra)


# ---------------------------------------------------------------------------
# crash hooks
# ---------------------------------------------------------------------------
_INSTALLED = {"done": False}
_install_lock = threading.Lock()


def install():
    """Chain sys.excepthook / threading.excepthook so an unhandled
    exception that ends the process leaves a bundle first.  Idempotent;
    called by the trainer/engine constructors so any process using the
    framework's entry points gets the black box for free.  The previous
    hooks still run — this observes, it does not swallow."""
    if not enabled():
        return
    with _install_lock:
        if _INSTALLED["done"]:
            return
        _INSTALLED["done"] = True
        prev_exc = sys.excepthook

        def _hook(etype, value, tb):
            note_event("unhandled_exception", type=etype.__name__,
                       message=str(value)[:500])
            dump("exception",
                 extra={"exception": "".join(
                     traceback.format_exception(etype, value, tb))[-8000:]})
            prev_exc(etype, value, tb)

        sys.excepthook = _hook
        prev_thread = threading.excepthook

        def _thook(args):
            # a crashing non-daemon thread can take the process down
            # too; record it, then defer to the previous hook
            note_event("thread_exception",
                       type=args.exc_type.__name__,
                       thread=getattr(args.thread, "name", "?"),
                       message=str(args.exc_value)[:500])
            prev_thread(args)

        threading.excepthook = _thook


def load_bundle(path: str) -> dict:
    """Read a dumped bundle dir back: {'bundle': ..., 'trace': ...}.
    Raises on a malformed bundle — the tests' validity check."""
    with open(os.path.join(path, "bundle.json")) as f:
        bundle = json.load(f)
    with open(os.path.join(path, "trace.json")) as f:
        trace = json.load(f)
    if bundle.get("format") != "paddle_tpu.flightrec.v1":
        raise ValueError(f"{path}: not a flightrec bundle")
    return {"bundle": bundle, "trace": trace}


_KEEP_DEFAULT = 32
_TMP_ORPHAN_AGE_S = 3600.0


def gc_bundles(directory: Optional[str] = None):
    """Bundle-dir GC, run at every dump: the per-process dump cap
    bounds ONE process, but a long-lived multi-replica fleet restarts
    processes for weeks and each leaves its 16 — prune the OLDEST
    committed bundle dirs beyond ``PADDLE_TPU_FLIGHTREC_KEEP`` (default
    32, by mtime so multi-process interleavings order correctly), and
    sweep ``.tmp`` staging orphans older than an hour (a crash mid-dump
    in a dead process; a live process's in-flight .tmp is younger and
    untouched).  Never raises — GC must not mask the failure being
    recorded."""
    import shutil
    base = directory or dump_dir()
    try:
        keep = int(os.environ.get("PADDLE_TPU_FLIGHTREC_KEEP",
                                  _KEEP_DEFAULT))
    except ValueError:
        keep = _KEEP_DEFAULT
    keep = max(keep, 1)
    try:
        names = os.listdir(base)
    except OSError:
        return
    now = time.time()
    committed = []
    for n in names:
        if not n.startswith("flightrec-"):
            continue
        p = os.path.join(base, n)
        if n.endswith(".tmp"):
            try:
                if now - os.path.getmtime(p) > _TMP_ORPHAN_AGE_S:
                    shutil.rmtree(p, ignore_errors=True)
            except OSError:
                pass
            continue
        try:
            committed.append((os.path.getmtime(p), p))
        except OSError:
            pass
    committed.sort()
    for _, p in committed[:max(len(committed) - keep, 0)]:
        shutil.rmtree(p, ignore_errors=True)


def find_bundles(directory: Optional[str] = None,
                 reason: Optional[str] = None) -> List[str]:
    """Committed bundle dirs under `directory` (default: dump_dir()),
    oldest first; `.tmp` staging orphans are invisible."""
    base = directory or dump_dir()
    try:
        names = sorted(os.listdir(base))
    except OSError:
        return []
    out = []
    for n in names:
        if not n.startswith("flightrec-") or n.endswith(".tmp"):
            continue
        if reason is not None and not n.endswith(f"-{reason}"):
            continue
        out.append(os.path.join(base, n))
    return out
