"""Stall watchdog + fleet straggler detection.

A wedged step loop is the failure telemetry is worst at: nothing
crashes, nothing logs, the metrics just stop moving.  The reference
framework's platform layer pairs its profiler with error machinery for
exactly this reason (PAPER.md §1 layer 0) — when progress stops you
want evidence captured AT the stall, not reconstructed after the kill.

:class:`Watchdog` is a monitor thread armed by the step loops
(``SpmdTrainer``/``GPipeTrainer`` per train step, ``InferenceEngine``
per decode tick).  The loop calls ``beat()`` — one ``time.monotonic``
store — and the monitor fires when no beat lands for ``timeout_s``:

- capture ALL-THREAD stacks (``sys._current_frames``) — the one
  artifact that says WHERE the process is stuck;
- write a flight-recorder bundle (reason ``stall``) with the stacks
  attached, so the ring + span tail + stuck frames land in one place;
- count it (``watchdog_stalls_total``) and, per ``on_stall``:
  ``"dump"`` (default) records and keeps watching, ``"raise"``
  additionally interrupts the main thread (KeyboardInterrupt at the
  stall site — a deliberately blunt instrument for harnesses that
  prefer death to a silent hang), or a callable gets the stall dict.

``idle()`` parks the watchdog (an empty serving engine between
requests is NOT a stall); the next ``beat()`` re-arms it.  A stall
fires ONCE per episode — the next beat resets the trigger.

Armed via ``PADDLE_TPU_WATCHDOG_S=<seconds>`` (unset/0 = off;
``PADDLE_TPU_WATCHDOG_ACTION=dump|raise``).  The per-step cost when
armed is one monotonic read + one attribute store.

Straggler detection is the fleet-level twin: a replica whose per-tick
wall time sits far above the fleet median drags every batch it serves.
:func:`detect_stragglers` turns per-replica mean tick times into a
verdict dict (median, ratios, flagged indexes) that
``run_fleet_loadtest`` and ``FleetAggregator`` surface in their
reports (``PADDLE_TPU_STRAGGLER_FACTOR``, default 1.75).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, List, Optional, Sequence, Union

from . import flightrec as _flightrec
from . import metrics as _metrics

__all__ = ["Watchdog", "watchdog_seconds", "detect_stragglers"]

_STRAGGLER_FACTOR_DEFAULT = 1.75


def watchdog_seconds() -> Optional[float]:
    """The armed timeout from PADDLE_TPU_WATCHDOG_S, or None (off)."""
    v = os.environ.get("PADDLE_TPU_WATCHDOG_S", "").strip()
    if not v:
        return None
    try:
        t = float(v)
    except ValueError:
        return None
    return t if t > 0 else None


class Watchdog:
    """No-progress monitor for one step/tick loop.

    Usage (what the trainers/engine do)::

        wd = Watchdog(timeout_s=30, label="spmd_train").arm()
        while training:
            wd.beat()
            train_step(...)
        wd.disarm()
    """

    def __init__(self, timeout_s: float, label: str = "loop",
                 on_stall: Union[str, Callable, None] = None,
                 poll_s: Optional[float] = None,
                 dump_dir: Optional[str] = None):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got "
                             f"{timeout_s}")
        self.timeout_s = float(timeout_s)
        self.label = label
        if on_stall is None:
            on_stall = os.environ.get("PADDLE_TPU_WATCHDOG_ACTION",
                                      "dump").strip() or "dump"
        if isinstance(on_stall, str) and on_stall not in ("dump",
                                                          "raise"):
            raise ValueError(
                f"on_stall must be 'dump', 'raise' or a callable, got "
                f"{on_stall!r}")
        self.on_stall = on_stall
        # poll fast enough that detection lands well inside the
        # configured window (stall seen within ~1.25 * timeout)
        self.poll_s = poll_s if poll_s is not None \
            else max(min(self.timeout_s / 4.0, 1.0), 0.01)
        self.dump_dir = dump_dir
        self.stalls = 0
        self.last_stall: Optional[dict] = None
        self._last_beat = time.monotonic()
        self._idle = True            # not a stall until the first beat
        self._fired = False          # one dump per stall episode
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_stalls = _metrics.counter(
            "watchdog_stalls_total", "no-progress stalls detected",
            labels=("label",)).labels(label=label)

    # ---- loop-side API (hot path) -------------------------------------
    def beat(self):
        """Heartbeat: the loop made progress (or is about to do a
        bounded unit of work).  Re-arms after idle() and closes a fired
        stall episode."""
        self._last_beat = time.monotonic()
        self._idle = False
        self._fired = False

    def idle(self):
        """No work to do — a quiet engine is not a stall."""
        self._idle = True

    # ---- lifecycle ----------------------------------------------------
    def arm(self) -> "Watchdog":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._monitor, name=f"watchdog-{self.label}",
                daemon=True)
            self._thread.start()
        return self

    def disarm(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.poll_s + 1.0)
            self._thread = None

    @property
    def stalled(self) -> bool:
        return self._fired

    # ---- monitor thread ----------------------------------------------
    def _monitor(self):
        while not self._stop.wait(self.poll_s):
            if self._idle or self._fired:
                continue
            age = time.monotonic() - self._last_beat
            if age <= self.timeout_s:
                continue
            self._fired = True
            self._handle_stall(age)

    def _handle_stall(self, age_s: float):
        self.stalls += 1
        self._m_stalls.inc()
        stacks = _flightrec.all_thread_stacks()
        info = {"label": self.label, "age_s": round(age_s, 3),
                "timeout_s": self.timeout_s, "stacks": stacks}
        _flightrec.note_event("watchdog_stall", label=self.label,
                              age_s=round(age_s, 3),
                              timeout_s=self.timeout_s)
        path = _flightrec.dump("stall", directory=self.dump_dir,
                               extra={"stall": {
                                   "label": self.label,
                                   "age_s": round(age_s, 3),
                                   "timeout_s": self.timeout_s}})
        info["bundle"] = path
        self.last_stall = info
        if callable(self.on_stall):
            try:
                self.on_stall(info)
            except Exception:       # a broken callback must not kill
                pass                # the monitor thread
        elif self.on_stall == "raise":
            import _thread
            _thread.interrupt_main()


# ---------------------------------------------------------------------------
# fleet straggler detection
# ---------------------------------------------------------------------------
def straggler_factor() -> float:
    v = os.environ.get("PADDLE_TPU_STRAGGLER_FACTOR", "").strip()
    try:
        return float(v) if v else _STRAGGLER_FACTOR_DEFAULT
    except ValueError:
        return _STRAGGLER_FACTOR_DEFAULT


def detect_stragglers(per_replica_ms: Sequence[Optional[float]],
                      factor: Optional[float] = None,
                      min_ms: float = 0.05) -> dict:
    """Per-replica step/tick-time skew vs the fleet median.

    ``per_replica_ms[i]`` is replica i's mean step/tick wall time over
    the measured window (None = replica did no work).  A replica is a
    straggler when its mean exceeds ``factor`` x the median of its
    PEERS (leave-one-out: a 2-replica fleet's overall median is
    dragged halfway to the straggler itself, which would hide exactly
    the skew the detector exists for) AND the absolute gap clears
    ``min_ms`` (sub-jitter skew on a fast CPU harness is noise, not a
    verdict).  Returns the report block::

        {"median_ms", "factor", "per_replica_ms", "ratio",
         "stragglers": [replica indexes]}

    ``median_ms``/``ratio`` quote the all-replica median (the number a
    dashboard plots); the flagging itself is leave-one-out.
    """
    import numpy as np
    factor = float(factor) if factor is not None else straggler_factor()
    vals = [(i, float(v)) for i, v in enumerate(per_replica_ms)
            if v is not None and v > 0]
    out = {"factor": factor,
           "per_replica_ms": [round(float(v), 3) if v is not None
                              else None for v in per_replica_ms],
           "median_ms": None, "ratio": None, "stragglers": []}
    if not vals:
        return out
    med = float(np.median([v for _, v in vals]))
    out["median_ms"] = round(med, 3)
    if med <= 0:
        return out
    valid = dict(vals)
    out["ratio"] = [round(valid[i] / med, 3) if i in valid else None
                    for i in range(len(per_replica_ms))]
    if len(vals) < 2:
        return out                  # no peers, no verdict
    for i, v in vals:
        peers = float(np.median([pv for pi, pv in vals if pi != i]))
        if peers > 0 and v > factor * peers and (v - peers) > min_ms:
            out["stragglers"].append(i)
    return out
