"""Automated perf doctor: rule-based bottleneck attribution.

ROADMAP item 1 ends every hardware run the same way: a human stares at
``comm_fraction + compile counters + HBM bytes`` and decides which knob
to turn next.  Every signal in that triage already exists in the stats
surfaces PRs 3-13 built — this module is the triage itself, encoded:
``diagnose(stats)`` runs a fixed rule table over the numbers a trainer
/ engine / bench row / loadgen report already carries and emits a
RANKED verdict list::

    [{"bottleneck": "comm-bound",
      "evidence": {"comm_fraction": 0.41, "top_op": "all-reduce"},
      "knob": "PADDLE_TPU_OVERLAP=1 / MoELayer a2a_chunks "
              "(PADDLE_TPU_MOE_A2A_CHUNKS) / revisit sharding stage",
      "score": 0.41}]

Rules fire only on evidence present in the dict (a missing or None
signal skips the rule — the doctor never invents a bottleneck), scores
normalize each signal into [0, 1]-ish "fraction of the step this
costs" so verdicts rank across rules, and the output is JSON-safe so
it rides ``trainer.stats['doctor']``, ``engine.stats['doctor']``,
every bench row and the loadgen report unchanged.

This is attribution, not enforcement: the doctor REPORTS.  The bench
smoke asserts only on deliberately-injected fixtures (a sync-heavy
loop must read host-sync-bound; a clean one must read clean).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional

__all__ = ["diagnose", "RULES", "Rule"]

# thresholds, one place (tests build fixtures against these)
COMM_FRACTION_MIN = 0.25
DATA_WAIT_FRACTION_MIN = 0.25
H2D_FRACTION_MIN = 0.25
SYNCS_PER_STEP_MIN = 0.75
SYNC_MS_FRACTION_MIN = 0.25
# fraction rules need a real window behind them: a 3-step CPU smoke
# whose whole wall clock is a few ms must not read as "bound" on
# anything — the fractions are noise until the window has substance
MIN_WINDOW_MS = 50.0
BLOCK_OCCUPANCY_MIN = 0.85
SPEC_ACCEPTANCE_MIN = 0.3
PREFIX_HIT_RATE_MIN = 0.15
PREFIX_QUERIES_MIN = 20
SLOT_OCCUPANCY_MIN = 0.5
# chunked prefill (ISSUE 20): share of the decode window spent running
# monolithic prefills while decode-phase slots sat idle
PREFILL_STALL_FRACTION_MIN = 0.15
# expert-parallel MoE serving (ISSUE 19): capacity-overflow drop rate
# and max/mean expert-load skew past these read as imbalance; the rule
# stays silent until real routed traffic backs the window
MOE_DROP_RATE_MAX = 0.05
MOE_LOAD_SKEW_MAX = 2.0
MOE_ASSIGNED_MIN = 64.0
# roofline/ledger rules (exec registry evidence, ISSUE 15)
HBM_BW_FRAC_MIN = 0.5      # decode pushing >= half the HBM roof
# multi-slice (DCN) tier rules
SLICE_AGE_FRAC_MIN = 0.5   # heartbeat age past half the slice timeout
DCN_SHARE_MIN = 0.4        # DCN bytes >= this share of collective bytes
DCN_COMM_FRACTION_MIN = 0.15
from .exec_registry import MFU_TARGET as MFU_GAP_MIN          # noqa: E402
from .exec_registry import OOM_HEADROOM_MIN as HBM_HEADROOM_MIN  # noqa: E402
# (one source of truth: the registry's attribution target and the
# ledger's oom_risk line — the doctor must agree with both surfaces)


def _num(stats: dict, key: str) -> Optional[float]:
    v = stats.get(key)
    return float(v) if isinstance(v, (int, float)) and not \
        isinstance(v, bool) else None


class Rule:
    """One named check: ``check(stats)`` returns (evidence, score) when
    it fires, None when the signal is absent or healthy.

    ``action`` is the MACHINE-readable form of ``knob`` (ISSUE 16): a
    dict ``{"op", "param", "env", "candidates"}`` — or a callable
    ``(stats, evidence) -> dict`` when the advice depends on the
    evidence (e.g. spec_k candidates below the CURRENT k).  ``op`` is
    the tuning-table namespace a winner commits under (None for advice
    with no table entry), ``param`` the config axis an autotune
    controller mutates (None for purely behavioral advice), ``env`` the
    equivalent environment knob, ``candidates`` the suggested trial
    values ([] defers to the controller's own axis defaults)."""

    def __init__(self, bottleneck: str, kinds: tuple, knob: str,
                 check: Callable[[dict], Optional[tuple]],
                 action=None):
        self.bottleneck = bottleneck
        self.kinds = kinds
        self.knob = knob
        self.check = check
        self.action = action

    def action_for(self, stats: dict, evidence: dict) -> Optional[dict]:
        """Resolve the structured action for one firing (JSON-safe copy;
        None when the rule has no machine-actionable form)."""
        a = self.action
        if callable(a):
            try:
                a = a(stats, evidence)
            except Exception:
                return None
        if not isinstance(a, dict):
            return None
        return {"op": a.get("op"), "param": a.get("param"),
                "env": a.get("env"),
                "candidates": list(a.get("candidates") or [])}


# ---------------------------------------------------------------------------
# train rules
# ---------------------------------------------------------------------------
def _comm_bound(s: dict):
    cf = _num(s, "comm_fraction")
    if cf is None or cf < COMM_FRACTION_MIN:
        return None
    ev = {"comm_fraction": round(cf, 4)}
    by_op = s.get("comm_by_op")
    if isinstance(by_op, dict) and by_op:
        top = max(by_op, key=lambda op: by_op[op].get("bytes", 0))
        ev["top_op"] = top
        ev["top_op_bytes"] = int(by_op[top].get("bytes", 0))
    return ev, cf


def _data_starved(s: dict):
    wait = _num(s, "data_wait_ms")
    disp = _num(s, "dispatch_ms")
    if wait is None or disp is None or (wait + disp) < MIN_WINDOW_MS:
        return None
    frac = wait / (wait + disp)
    if frac < DATA_WAIT_FRACTION_MIN:
        return None
    return {"data_wait_ms": round(wait, 2),
            "dispatch_ms": round(disp, 2),
            "data_wait_fraction": round(frac, 4)}, frac


def _h2d_bound(s: dict):
    h2d = _num(s, "h2d_ms")
    disp = _num(s, "dispatch_ms")
    if h2d is None or disp is None or (h2d + disp) < MIN_WINDOW_MS:
        return None
    frac = h2d / (h2d + disp)
    if frac < H2D_FRACTION_MIN:
        return None
    return {"h2d_ms": round(h2d, 2), "dispatch_ms": round(disp, 2),
            "h2d_fraction": round(frac, 4)}, frac


def _host_sync_bound(s: dict):
    # preferred evidence: a measured sync count over a step window
    # (bench rows / the smoke fixture carry host_syncs_measured+steps);
    # fallback: the trainer's cumulative sync wall-time share
    syncs = _num(s, "host_syncs_measured")
    steps = _num(s, "steps") or _num(s, "steps_timed")
    if syncs is not None and steps and steps > 0:
        per_step = syncs / steps
        if per_step < SYNCS_PER_STEP_MIN:
            return None
        return {"host_syncs_measured": int(syncs), "steps": int(steps),
                "syncs_per_step": round(per_step, 3)}, min(per_step, 2.0)
    sync_ms = _num(s, "sync_ms")
    disp = _num(s, "dispatch_ms")
    if sync_ms is None or disp is None or \
            (sync_ms + disp) < MIN_WINDOW_MS:
        return None
    frac = sync_ms / (sync_ms + disp)
    if frac < SYNC_MS_FRACTION_MIN:
        return None
    return {"sync_ms": round(sync_ms, 2), "dispatch_ms": round(disp, 2),
            "sync_fraction": round(frac, 4)}, frac


def _recompile_churn(s: dict):
    # only the POST-WARMUP delta is evidence (engine-lifetime compile
    # counts legitimately include warmup); bench rows and the smokes
    # carry it as xla_compiles_measured
    n = _num(s, "xla_compiles_measured")
    if n is None or n <= 0:
        return None
    return {"xla_compiles_measured": int(n)}, min(1.0, 0.5 + n / 10.0)


# ---------------------------------------------------------------------------
# serve rules
# ---------------------------------------------------------------------------
def _kv_pressure(s: dict):
    occ = _num(s, "block_occupancy")
    pre = _num(s, "preemptions") or 0.0
    if (occ is None or occ < BLOCK_OCCUPANCY_MIN) and pre <= 0:
        return None
    ev = {}
    if occ is not None:
        ev["block_occupancy"] = round(occ, 4)
    if pre:
        ev["preemptions"] = int(pre)
    score = max(occ or 0.0, min(1.0, 0.5 + pre / 20.0))
    return ev, score


def _low_spec_acceptance(s: dict):
    acc = _num(s, "spec_acceptance_rate")
    if acc is None or acc >= SPEC_ACCEPTANCE_MIN:
        return None
    ev = {"spec_acceptance_rate": round(acc, 4)}
    apt = _num(s, "accepted_tokens_per_tick")
    if apt is not None:
        ev["accepted_tokens_per_tick"] = round(apt, 3)
    return ev, 1.0 - acc


def _prefix_cold(s: dict):
    hit = _num(s, "prefix_hit_rate")
    q = _num(s, "prefix_queries")
    if hit is None or q is None or q < PREFIX_QUERIES_MIN or \
            hit >= PREFIX_HIT_RATE_MIN:
        return None
    return {"prefix_hit_rate": round(hit, 4),
            "prefix_queries": int(q)}, 0.5 * (1.0 - hit)


def _prefill_stall(s: dict):
    """Monolithic prefill stalls running decodes: the engine's
    ``prefill_stall_ms`` counter accumulates the wall time prefill
    executables ran while decode-phase requests sat idle in their
    slots (ISSUE 20).  Evidence is the stall's share of the decode
    window; chunked mode zeroes the counter by construction, so the
    rule is structurally silent once its own advice is taken."""
    if s.get("chunked_prefill"):
        return None                     # the fix is already on
    stall = _num(s, "prefill_stall_ms")
    dec = _num(s, "decode_ms")
    if not stall or dec is None or (stall + dec) < MIN_WINDOW_MS:
        return None
    frac = stall / (stall + dec)
    if frac < PREFILL_STALL_FRACTION_MIN:
        return None
    ev = {"prefill_stall_ms": round(stall, 2),
          "decode_ms": round(dec, 2),
          "stall_fraction": round(frac, 4)}
    p99 = _num(s, "itl_ms_p99")
    if p99 is not None:
        ev["itl_ms_p99"] = round(p99, 3)
    return ev, frac


def _idle_slots(s: dict):
    occ = _num(s, "slot_occupancy")
    pre = _num(s, "preemptions") or 0.0
    if occ is None or occ >= SLOT_OCCUPANCY_MIN or pre > 0:
        # preemption-driven emptiness is kv-pressure's verdict, not
        # admission's
        return None
    steps = _num(s, "decode_steps")
    if steps is None or steps < 8:      # too few ticks to call it
        return None
    return {"slot_occupancy": round(occ, 4),
            "decode_steps": int(steps)}, 0.5 * (1.0 - occ)


def _exec_prof(s: dict, *kinds) -> Optional[dict]:
    """The exec-registry roofline digest riding stats['exec_profile']
    (observability.exec_registry.profile): first matching kind's row,
    or None.  Nominal-peak digests (host backends) are ignored unless
    PADDLE_TPU_ROOFLINE_DOCTOR=1 forces them — a laptop smoke must not
    read as a TPU roofline verdict."""
    prof = s.get("exec_profile")
    if not isinstance(prof, dict):
        return None
    peaks = prof.get("_peaks") or {}
    if peaks.get("peaks_nominal") and \
            os.environ.get("PADDLE_TPU_ROOFLINE_DOCTOR") != "1":
        return None
    for k in kinds:
        row = prof.get(k)
        if isinstance(row, dict):
            return row
    return None


def _hbm_heavy_decode(s: dict):
    """Roofline-aware decode verdict: with the exec registry analyzed,
    the evidence is the MEASURED bandwidth fraction ("decode achieves
    72% of peak HBM BW → bandwidth-bound"); without it, fall back to
    the old threshold heuristic (bytes/token with no byte-saver on)."""
    steps = _num(s, "decode_steps")
    if steps is None or steps < 8:
        return None
    kv = s.get("kv_dtype")
    mk = s.get("decode_megakernel")
    saver_on = kv not in (None, "dense") or bool(mk)
    row = _exec_prof(s, "decode", "megakernel_decode", "spec_verify")
    if row is not None and row.get("bound"):
        # measured roofline evidence is AUTHORITATIVE: a compute-bound
        # or below-the-floor decode must not fall through to the byte
        # heuristic and contradict the measurement
        if row["bound"] != "bandwidth" or \
                row.get("hbm_bw_frac") is None or \
                float(row["hbm_bw_frac"]) < HBM_BW_FRAC_MIN:
            return None
        frac = float(row["hbm_bw_frac"])
        ev = {"hbm_bw_frac": round(frac, 4),
              "achieved_hbm_gbps": row.get("achieved_hbm_gbps"),
              "arithmetic_intensity": row.get("arithmetic_intensity"),
              "ridge_ai": row.get("ridge_ai"),
              "bound": "bandwidth",
              "kv_dtype": kv or "dense",
              "decode_megakernel": bool(mk)}
        if row.get("mfu") is not None:
            ev["mfu"] = row["mfu"]
        # a byte-saver already on shrinks the verdict to informational
        return ev, (min(frac, 1.0) if not saver_on else 0.15)
    # threshold fallback (pre-registry evidence only)
    hbm = _num(s, "decode_hbm_bytes_per_tok")
    if hbm is None or saver_on:
        return None
    return {"decode_hbm_bytes_per_tok": int(hbm),
            "kv_dtype": kv or "dense",
            "decode_megakernel": bool(mk)}, 0.3


def _roofline_train(s: dict):
    """Train-step roofline attribution: the fused step's measured MFU
    against the 45% target, classified compute- vs bandwidth-bound so
    the knob is the right one (quantize/flash for compute, remat/batch
    for bandwidth)."""
    row = _exec_prof(s, "train_step", "pipeline_tick")
    if row is None or row.get("mfu") is None or not row.get("bound"):
        return None
    mfu = float(row["mfu"])
    if mfu >= MFU_GAP_MIN:
        return None                     # at/near target: nothing to say
    ev = {"mfu": round(mfu, 4), "bound": row["bound"],
          "arithmetic_intensity": row.get("arithmetic_intensity"),
          "ridge_ai": row.get("ridge_ai"),
          "mean_ms": row.get("mean_ms")}
    if row.get("hbm_bw_frac") is not None:
        ev["hbm_bw_frac"] = row["hbm_bw_frac"]
    if row.get("gap_share") is not None:
        ev["gap_share"] = row["gap_share"]
    return ev, min(1.0, (MFU_GAP_MIN - mfu) / MFU_GAP_MIN)


def _oom_risk(s: dict):
    """HBM-ledger headroom: tracked state + worst executable temp
    against device capacity.  Fires before the OOM does."""
    h = s.get("hbm")
    if not isinstance(h, dict):
        return None
    frac = h.get("headroom_frac")
    if not isinstance(frac, (int, float)) or frac >= HBM_HEADROOM_MIN:
        return None
    ev = {"headroom_frac": round(float(frac), 4),
          "tracked_bytes": h.get("tracked_bytes"),
          "capacity_bytes": h.get("capacity_bytes"),
          "exec_temp_bytes": h.get("exec_temp_bytes")}
    if h.get("exec_temp_worst"):
        ev["exec_temp_worst"] = h["exec_temp_worst"]
    return ev, min(1.0, 1.0 - float(frac))


# ---------------------------------------------------------------------------
# evidence-dependent actions (callables: (stats, evidence) -> action dict)
# ---------------------------------------------------------------------------
def _spec_k_action(s: dict, ev: dict) -> dict:
    """Candidates are spec_k values BELOW the current window — a low
    acceptance rate never argues for drafting further ahead."""
    cur = s.get("spec_k")
    cands: list = []
    if isinstance(cur, (int, float)) and not isinstance(cur, bool):
        k = int(cur)
        while k > 1:
            k //= 2
            cands.append(max(k, 1))
            if cands[-1] == 1:
                break
    return {"op": None, "param": "spec_k", "env": "PADDLE_TPU_SPEC_K",
            "candidates": cands or [1, 2]}


def _decode_bw_action(s: dict, ev: dict) -> dict:
    """First byte-saver not already on: megakernel, then int8 KV, then
    speculative decoding to amortize the streamed bytes."""
    if not s.get("decode_megakernel"):
        return {"op": "megakernel_blocks", "param": "decode_megakernel",
                "env": "PADDLE_TPU_DECODE_MEGAKERNEL",
                "candidates": [True]}
    if s.get("kv_dtype") in (None, "dense"):
        return {"op": None, "param": "kv_dtype",
                "env": "PADDLE_TPU_KV_DTYPE", "candidates": ["int8"]}
    return {"op": None, "param": "spec_k", "env": "PADDLE_TPU_SPEC_K",
            "candidates": [2, 4]}


def _mfu_action(s: dict, ev: dict) -> dict:
    """Compute-bound gap → cheaper math (quantize); bandwidth-bound →
    recompute less (remat policy A/B) so the bytes drop."""
    if ev.get("bound") == "compute":
        return {"op": "qmm_tiles", "param": "quantize",
                "env": "BENCH_QUANTIZE", "candidates": ["int8"]}
    return {"op": "remat_policy", "param": "remat_policy", "env": None,
            "candidates": ["off", "dots_no_batch", "dots", "full"]}


def _oom_action(s: dict, ev: dict) -> dict:
    """Serving evidence (kv_dtype/decode slots present) → shrink the KV;
    training → turn remat up."""
    if "kv_dtype" in s or "decode_steps" in s or "block_occupancy" in s:
        return {"op": None, "param": "kv_dtype",
                "env": "PADDLE_TPU_KV_DTYPE", "candidates": ["int8"]}
    return {"op": "remat_policy", "param": "remat_policy", "env": None,
            "candidates": ["full", "dots"]}


def _expert_imbalance(s: dict):
    """MoE serving routes tokens badly: capacity overflow is DROPPING
    token→expert assignments (quality loss — the dropped token skips
    its expert FFN), or the hottest expert carries a multiple of the
    mean load (its device bounds every a2a round-trip while the cold
    experts idle).  Evidence only on real traffic."""
    n_exp = _num(s, "moe_num_experts")
    assigned = _num(s, "moe_assigned_tokens")
    if not n_exp or assigned is None or assigned < MOE_ASSIGNED_MIN:
        return None
    drop = _num(s, "moe_dropped_rate") or 0.0
    skew = _num(s, "moe_load_skew")
    if drop < MOE_DROP_RATE_MAX and \
            (skew is None or skew < MOE_LOAD_SKEW_MAX):
        return None
    ev = {"moe_dropped_rate": round(drop, 4),
          "moe_num_experts": int(n_exp),
          "moe_assigned_tokens": round(assigned, 1)}
    if skew is not None:
        ev["moe_load_skew"] = round(skew, 3)
    ep = _num(s, "ep")
    if ep and ep > 1:
        ev["ep"] = int(ep)
    load = s.get("moe_expert_load")
    if isinstance(load, (list, tuple)) and load:
        ev["hottest_expert"] = max(range(len(load)),
                                   key=lambda i: load[i])
    score = max(drop / MOE_DROP_RATE_MAX,
                (skew or 0.0) / MOE_LOAD_SKEW_MAX) * 0.5
    return ev, min(score, 1.0)


def _moe_imbalance_action(s: dict, ev: dict) -> dict:
    """Overflow drops → more room per expert (capacity factor above
    the training default).  Pure skew with speculative decoding on →
    shrink the verify burst first (spec_k multiplies the tokens a hot
    expert sees per tick); otherwise the capacity raise still buys
    headroom for the hot expert."""
    if ev.get("moe_dropped_rate", 0.0) < MOE_DROP_RATE_MAX \
            and s.get("spec_k"):
        return _spec_k_action(s, ev)
    return {"op": None, "param": "moe_capacity_factor", "env": None,
            "candidates": [1.5, 2.0, 2.5]}


def _slice_unhealthy(s: dict):
    """A DCN slice's heartbeat is stale (past half its timeout) or
    already declared dead — the membership layer is about to (or did)
    escalate; evidence names the worst slice so an operator can find
    the sick hosts before the reform, not after."""
    ages = s.get("slice_heartbeat_ages")
    timeout = _num(s, "slice_timeout_s")
    if not isinstance(ages, dict) or not ages or not timeout \
            or timeout <= 0:
        return None
    worst_id, worst = None, -1.0
    for sid, age in ages.items():
        if isinstance(age, (int, float)) and not isinstance(age, bool) \
                and float(age) > worst:
            worst_id, worst = sid, float(age)
    dead = s.get("slices_dead") or []
    if worst_id is None and not dead:
        return None
    frac = (worst / timeout) if worst >= 0 else 0.0
    if frac < SLICE_AGE_FRAC_MIN and not dead:
        return None
    ev = {"timeout_s": timeout}
    if worst_id is not None:
        ev["slice"] = worst_id
        ev["heartbeat_age_s"] = round(worst, 3)
    if dead:
        ev["slices_dead"] = list(dead)
    reforms = _num(s, "mesh_reforms")
    if reforms:
        ev["mesh_reforms"] = int(reforms)
    score = max(frac, 1.0) if dead else frac
    return ev, min(score, 2.0)


def _dcn_bound(s: dict):
    """Cross-slice (DCN) all-reduce dominates the collective bytes AND
    communication is a real share of the step: the slow tier is the
    bottleneck — sync less often or move less across slices."""
    dcn_b = _num(s, "comm_bytes_dcn")
    total = _num(s, "comm_bytes")
    cf = _num(s, "comm_fraction")
    if not dcn_b or not total or total <= 0 or cf is None:
        return None
    share = dcn_b / total
    if share < DCN_SHARE_MIN or cf < DCN_COMM_FRACTION_MIN:
        return None
    ev = {"dcn_bytes": int(dcn_b), "comm_bytes": int(total),
          "dcn_share": round(share, 4), "comm_fraction": round(cf, 4)}
    return ev, min(cf * (1.0 + share), 2.0)


RULES: List[Rule] = [
    Rule("slice-unhealthy", ("train",),
         "a DCN slice's heartbeat is stale: check its hosts / expect an "
         "in-memory mesh reform (lost-slice reshard); tune "
         "PADDLE_TPU_SLICE_HB_TIMEOUT_S for the detection window",
         _slice_unhealthy,
         # behavioral/operational: no tuning-table axis moves this
         action={"op": None, "param": None,
                 "env": "PADDLE_TPU_SLICE_HB_TIMEOUT_S",
                 "candidates": []}),
    Rule("dcn-bound", ("train",),
         "cross-slice all-reduce dominates: gradient_merge (k_steps) to "
         "sync across slices less often / larger per-slice batch / keep "
         "overlap on (PADDLE_TPU_OVERLAP=1)",
         _dcn_bound,
         action={"op": None, "param": "k_steps", "env": None,
                 "candidates": [2, 4, 8]}),
    Rule("comm-bound", ("train",),
         "PADDLE_TPU_OVERLAP=1 / MoELayer a2a_chunks "
         "(PADDLE_TPU_MOE_A2A_CHUNKS) / revisit sharding stage",
         _comm_bound,
         action={"op": "moe_a2a_chunks", "param": "moe_a2a_chunks",
                 "env": "PADDLE_TPU_MOE_A2A_CHUNKS",
                 "candidates": [1, 2, 4, 8]}),
    Rule("data-starved", ("train",),
         "raise prefetch_depth (PADDLE_TPU_PREFETCH_DEPTH) / add "
         "DataLoader workers / check input storage",
         _data_starved,
         action={"op": None, "param": "prefetch_depth",
                 "env": "PADDLE_TPU_PREFETCH_DEPTH",
                 "candidates": [2, 4, 8]}),
    Rule("h2d-bound", ("train",),
         "keep DevicePrefetcher on (PADDLE_TPU_PREFETCH_DEPTH>0) / "
         "shrink host-side batch copies",
         _h2d_bound,
         action={"op": None, "param": "prefetch_depth",
                 "env": "PADDLE_TPU_PREFETCH_DEPTH",
                 "candidates": [2, 4]}),
    Rule("host-sync-bound", ("train", "serve"),
         "keep StepResult lazy (no per-step float(loss)/np.asarray); "
         "read stats at log boundaries; anomaly_policy=rollback costs "
         "1 sync/step",
         _host_sync_bound,
         # behavioral: no config axis turns this — the fix is in the
         # caller's code, so the controller must skip it
         action={"op": None, "param": None, "env": None,
                 "candidates": []}),
    Rule("recompile-churn", ("train", "serve"),
         "pin shapes: prefill buckets (PADDLE_TPU_PREFILL_BUCKETS), "
         "fixed batch/seq, persistent compile cache "
         "(PADDLE_TPU_COMPILE_CACHE)",
         _recompile_churn,
         action={"op": "prefill_buckets", "param": "prefill_buckets",
                 "env": "PADDLE_TPU_PREFILL_BUCKETS",
                 "candidates": []}),
    Rule("kv-pressure", ("serve",),
         "raise PADDLE_TPU_KV_BLOCKS / int8 KV "
         "(PADDLE_TPU_KV_DTYPE=int8) / lower max_new_tokens",
         _kv_pressure,
         action={"op": None, "param": "kv_dtype",
                 "env": "PADDLE_TPU_KV_DTYPE", "candidates": ["int8"]}),
    Rule("low-spec-acceptance", ("serve",),
         "lower spec_k (PADDLE_TPU_SPEC_K) / use a better-matched "
         "draft model",
         _low_spec_acceptance, action=_spec_k_action),
    Rule("prefix-cold", ("serve",),
         "enable the radix prefix cache (PADDLE_TPU_PREFIX_CACHE=1) / "
         "prefix-aware routing (Router policy='prefix')",
         _prefix_cold,
         action={"op": None, "param": "prefix_cache",
                 "env": "PADDLE_TPU_PREFIX_CACHE",
                 "candidates": [True]}),
    Rule("prefill-stall", ("serve",),
         "enable chunked prefill (PADDLE_TPU_CHUNKED_PREFILL=<chunk> / "
         "engine prefill_chunk=) so prompts are fed through the decode "
         "tick in fixed-budget chunks instead of stalling the batch",
         _prefill_stall,
         action={"op": None, "param": "prefill_chunk",
                 "env": "PADDLE_TPU_CHUNKED_PREFILL",
                 "candidates": [32, 64, 128]}),
    Rule("admission-bound", ("serve",),
         "raise batch_slots (PADDLE_TPU_DECODE_SLOTS) / check arrival "
         "rate vs capacity",
         _idle_slots,
         action={"op": None, "param": "batch_slots",
                 "env": "PADDLE_TPU_DECODE_SLOTS", "candidates": []}),
    Rule("expert-imbalance", ("serve",),
         "raise moe_capacity_factor (GPTConfig) so the capacity "
         "buckets stop dropping assignments / lower spec_k "
         "(PADDLE_TPU_SPEC_K) to shrink the verify burst a hot expert "
         "absorbs / rebalance gating (aux loss weight) upstream",
         _expert_imbalance, action=_moe_imbalance_action),
    Rule("bandwidth-bound-decode", ("serve",),
         "enable the decode megakernel (PADDLE_TPU_DECODE_MEGAKERNEL=1)"
         " / int8 KV (PADDLE_TPU_KV_DTYPE=int8) / speculative decoding "
         "(PADDLE_TPU_SPEC_K) to amortize the streamed bytes",
         _hbm_heavy_decode, action=_decode_bw_action),
    Rule("mfu-below-target", ("train",),
         "compute-bound: quantize=int8 (BENCH_QUANTIZE) / flash "
         "attention / remat off; bandwidth-bound: larger batch / "
         "fused_ce / scan_layers — see exec_profile gap_share for the "
         "executable owning the gap",
         _roofline_train, action=_mfu_action),
    Rule("oom-risk", ("train", "serve"),
         "int8 KV (PADDLE_TPU_KV_DTYPE=int8) / fewer decode slots "
         "(PADDLE_TPU_DECODE_SLOTS) or KV blocks (PADDLE_TPU_KV_BLOCKS)"
         " / smaller batch / remat on (strategy.recompute)",
         _oom_risk, action=_oom_action),
]


def diagnose(stats: dict, kind: Optional[str] = None) -> List[dict]:
    """Run the rule table over one stats dict; returns the ranked
    verdict list (empty = no bottleneck the rules can see).  `kind`
    restricts the table ('train' | 'serve'; loadgen reports pass
    'serve' — their columns are the serving ones); None runs every
    rule, letting the keys present decide."""
    out: List[Dict] = []
    for rule in RULES:
        if kind is not None and kind not in rule.kinds:
            continue
        try:
            hit = rule.check(stats)
        except Exception:               # a broken rule must never take
            continue                    # a stats read down
        if hit is None:
            continue
        evidence, score = hit
        verdict = {"bottleneck": rule.bottleneck,
                   "evidence": evidence,
                   "knob": rule.knob,
                   "score": round(float(score), 4)}
        action = rule.action_for(stats, evidence)
        if action is not None:
            verdict["action"] = action
        out.append(verdict)
    out.sort(key=lambda v: -v["score"])
    return out
