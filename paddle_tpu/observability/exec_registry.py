"""Executable observatory: per-executable cost/memory registry + HBM
ledger + roofline attribution.

The telemetry layer (metrics/spans) and the flight recorder/doctor say
*that* a step is slow; nothing says *which compiled executable* eats
the time and whether it is compute- or bandwidth-bound — the evidence
ROADMAP item 1's hardware MFU run needs to pick the next knob.  The
reference framework attributes cost per-op through its profiler/kernel
registry (PAPER.md §1 layer 0); our unit of attribution is the XLA
executable, and this registry is also the scouting party for ROADMAP
item 5's unified ``Executable`` abstraction: every entry point that
compiles something (SpmdTrainer fused step, GPipeTrainer tick, engine
prefill buckets, dense/paged decode, spec verify tick, megakernel
decode, disagg prefill worker, bench candidates) registers it here.

Three pieces:

- **ExecRegistry** — one entry per compiled executable, keyed
  ``(component, key)`` where ``component`` names the owner ("engine:e0",
  "trainer:s1") and ``key`` is the owner's own executable key
  (("prefill", 128), ("fused", 1, 1), ...).  Registration happens at
  compile time (the owner's first-call branch) and captures the name /
  kind / shape key / compile wall ms / donation config / input-sharding
  summary plus ShapeDtypeStructs of the call args; runtime pairing
  (``note_runtime``) is one dict lookup + two float adds per steady
  call — ZERO host syncs, zero jax calls, so arming the registry costs
  the hot path nothing (the contract tests/test_telemetry.py asserts).
  XLA ``cost_analysis`` / ``memory_analysis`` are EXPLICITLY deferred:
  ``analyze()`` AOT re-lowers the executable from the stored shape
  structs (a compile that the persistent cache serves as a deserialize)
  — bench legs, the report CLI and tests arm it; the decode loop never
  pays it and never recompiles after warmup.  Owners are held by
  WEAKREF: a dead engine's entries degrade to timing-only instead of
  pinning its params in HBM (bench candidate teardown relies on that).
- **Roofline** — per-device-kind peak FLOP/s and HBM GB/s tables (the
  bench.py device-kind lookup, extended with bandwidth + host-backend
  nominals so CPU smokes exercise the same math).  Each analyzed entry
  reports achieved FLOP/s, achieved HBM bandwidth, arithmetic
  intensity, its ridge point, compute-vs-bandwidth classification,
  fraction of its own roof, MFU, and an MFU *attribution*: the share
  of the measured wall clock it owns and the share of the gap to the
  45% target chargeable to it.
- **HBMLedger** — live device-memory accounting: params, optimizer
  state, KV pools, draft caches tracked by their owners (weakref'd, so
  dead owners fall out), plus the worst per-executable temp/peak bytes
  the analyses surfaced, against device capacity
  (``device.memory_stats()['bytes_limit']`` where the backend exposes
  it, else a per-device-kind table, else ``PADDLE_TPU_HBM_BYTES``).
  Yields a headroom gauge and the doctor's oom-risk evidence.

Knobs: ``PADDLE_TPU_EXEC_REGISTRY=0`` disables registration entirely;
``PADDLE_TPU_PEAK_FLOPS`` / ``PADDLE_TPU_PEAK_HBM_GBPS`` /
``PADDLE_TPU_HBM_BYTES`` override the device tables (tests and exotic
parts use these).
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import metrics as _metrics

__all__ = [
    "ExecEntry", "ExecRegistry", "HBMLedger", "registry", "ledger",
    "register", "note_runtime", "analyze_all", "profile",
    "profile_from_snapshot", "snapshot", "track_bytes", "tree_bytes",
    "enabled", "device_kind", "peak_flops", "peak_hbm_bytes_per_s",
    "device_hbm_capacity", "MFU_TARGET", "OOM_HEADROOM_MIN",
]

MFU_TARGET = 0.45          # the ROADMAP item 1 north star
OOM_HEADROOM_MIN = 0.08    # headroom fraction below which = oom risk
# (shared with doctor.HBM_HEADROOM_MIN so the ledger's oom_risk flag
# and the doctor's oom-risk verdict can never disagree on the line)

# peak dense bf16 FLOP/s per chip by device kind (public spec sheets).
# NB: v5e's headline 394 TFLOPS is the INT8 number; bf16 peak is 197.
# This is the authoritative copy of the table bench.py grew for MFU —
# bench.peak_flops delegates here now.
PEAK_FLOPS_BF16 = {
    "v5 lite": 197e12, "v5e": 197e12,
    "v5p": 459e12, "v5": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12, "v6e": 918e12,
    "v3": 61.5e12,  # per chip-half (device == core on v3)
    "v2": 22.5e12,
}

# peak HBM bandwidth per chip (GB/s, public spec sheets)
PEAK_HBM_GBPS = {
    "v5 lite": 819.0, "v5e": 819.0,
    "v5p": 2765.0, "v5": 2765.0,
    "v4": 1228.0,
    "v6 lite": 1640.0, "v6e": 1640.0,
    "v3": 900.0,
    "v2": 700.0,
}

# HBM capacity per chip (bytes) for backends whose memory_stats() is
# unavailable; same device-kind matching
HBM_CAPACITY_BYTES = {
    "v5 lite": 16 << 30, "v5e": 16 << 30,
    "v5p": 95 << 30, "v5": 95 << 30,
    "v4": 32 << 30,
    "v6 lite": 32 << 30, "v6e": 32 << 30,
    "v3": 16 << 30,
    "v2": 8 << 30,
}

# nominal host-backend figures: CPU smokes run the same roofline MATH
# (AI classification, fractions) without claiming hardware numbers —
# snapshots carry peaks_nominal=True so the doctor does not diagnose a
# laptop as a TPU
HOST_PEAK_FLOPS = 5e10
HOST_PEAK_HBM_GBPS = 10.0


def enabled() -> bool:
    return os.environ.get("PADDLE_TPU_EXEC_REGISTRY", "1") != "0"


def device_kind() -> str:
    try:
        import jax
        return getattr(jax.devices()[0], "device_kind", "").lower()
    except Exception:
        return ""


def _kind_lookup(table: Dict[str, float], kind: Optional[str]
                 ) -> Optional[float]:
    kind = (kind if kind is not None else device_kind()).lower()
    for key in sorted(table, key=len, reverse=True):
        if key in kind:
            return table[key]
    return None


def peak_flops(kind: Optional[str] = None) -> Tuple[float, bool]:
    """(peak FLOP/s, nominal?) for a device kind.  Env
    PADDLE_TPU_PEAK_FLOPS overrides (treated as authoritative); unknown
    kinds get the host nominal with nominal=True."""
    env = os.environ.get("PADDLE_TPU_PEAK_FLOPS")
    if env:
        return float(env), False
    hit = _kind_lookup(PEAK_FLOPS_BF16, kind)
    return (hit, False) if hit else (HOST_PEAK_FLOPS, True)


def peak_hbm_bytes_per_s(kind: Optional[str] = None) -> Tuple[float, bool]:
    """(peak HBM bytes/s, nominal?); PADDLE_TPU_PEAK_HBM_GBPS
    overrides."""
    env = os.environ.get("PADDLE_TPU_PEAK_HBM_GBPS")
    if env:
        return float(env) * 1e9, False
    hit = _kind_lookup(PEAK_HBM_GBPS, kind)
    return (hit * 1e9, False) if hit else (HOST_PEAK_HBM_GBPS * 1e9, True)


def device_hbm_capacity() -> Optional[int]:
    """Device memory capacity in bytes: PADDLE_TPU_HBM_BYTES override,
    else the runtime's own memory_stats()['bytes_limit'], else the
    per-kind table, else None (host backends — unknown)."""
    env = os.environ.get("PADDLE_TPU_HBM_BYTES")
    if env:
        return int(float(env))
    try:
        import jax
        dev = jax.local_devices()[0]
        ms = dev.memory_stats() if hasattr(dev, "memory_stats") else None
        if ms and ms.get("bytes_limit"):
            return int(ms["bytes_limit"])
    except Exception:
        pass
    hit = _kind_lookup(HBM_CAPACITY_BYTES, None)
    return int(hit) if hit else None


def tree_bytes(tree) -> int:
    """Host-side byte count of a pytree of arrays (shape/dtype math
    only — never syncs, never touches device data)."""
    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return int(total)


def _sds(a):
    """A leaf's ShapeDtypeStruct (sharding-preserving when the leaf is
    a committed jax.Array) — what analyze() re-lowers from, so the
    registry never keeps device buffers alive."""
    import jax
    shape = getattr(a, "shape", None)
    dtype = getattr(a, "dtype", None)
    if shape is None or dtype is None:
        return a
    sh = getattr(a, "sharding", None)
    if sh is not None:
        try:
            return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sh)
        except Exception:
            pass
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _sharding_summary(args) -> List[str]:
    """Compact per-arg sharding strings for registered call args (first
    leaf of each arg; replicated/single-device collapse to 'single')."""
    import jax
    out = []
    for a in args:
        leaves = jax.tree_util.tree_leaves(a)
        if not leaves:
            out.append("-")
            continue
        sh = getattr(leaves[0], "sharding", None)
        if sh is None:
            out.append("host")
        else:
            s = str(sh)
            out.append("single" if "SingleDevice" in s else s[:120])
    return out


class ExecEntry:
    """One compiled executable's observatory record."""

    def __init__(self, component: str, key, kind: str, name: str,
                 donate_argnums=(), meta: Optional[dict] = None):
        self.component = component
        self.key = key
        self.kind = kind
        self.name = name
        self.donate_argnums = tuple(donate_argnums or ())
        self.meta = dict(meta or {})
        self.created = time.time()
        self.compile_ms: Optional[float] = None
        # steady-state pairing (note_runtime): GIL-atomic adds only
        self.calls = 0
        self.runtime_ms = 0.0
        # deferred XLA analysis
        self.analysis: Optional[dict] = None
        self.analysis_error: Optional[str] = None
        self.in_shardings: List[str] = []
        self._jit_ref = None            # weakref to the jitted callable
        self._arg_shapes = None         # SDS pytree for analyze()

    @property
    def alive(self) -> bool:
        return self._jit_ref is not None and self._jit_ref() is not None


class ExecRegistry:
    """Process-wide executable registry (one instance — ``registry()``;
    tests may build private ones)."""

    _CAP = 1024     # safety bound; dead-owner entries evicted first

    def __init__(self):
        self._entries: Dict[Tuple[str, Any], ExecEntry] = {}
        self._lock = threading.Lock()
        self._m_registered = _metrics.counter(
            "exec_registered_total", "executables joined the registry",
            labels=("kind",))
        self._m_failures = _metrics.counter(
            "exec_analysis_failures_total",
            "executable cost/memory analyses that degraded to "
            "timing-only", labels=("stage",))

    # ---- registration (compile-time; cheap) ---------------------------
    def register(self, component: str, key, kind: str, jitfn=None,
                 args=(), donate_argnums=(), meta: Optional[dict] = None,
                 name: Optional[str] = None) -> Optional[ExecEntry]:
        """Join one executable at compile time.  Call BEFORE invoking
        the executable so the arg shape structs are captured while the
        (possibly donated) buffers are still readable.  Idempotent per
        (component, key)."""
        if not enabled():
            return None
        k = (component, key)
        e = self._entries.get(k)
        if e is not None:
            return e
        e = ExecEntry(component, key, kind,
                      name or _default_name(key, kind),
                      donate_argnums=donate_argnums, meta=meta)
        try:
            import jax
            if jitfn is not None:
                e._jit_ref = weakref.ref(jitfn)
            e._arg_shapes = jax.tree_util.tree_map(_sds, tuple(args))
            e.in_shardings = _sharding_summary(args)
        except Exception as exc:   # registration must never take a step
            e.analysis_error = (f"register: {type(exc).__name__}: "
                                f"{str(exc)[:200]}")
        with self._lock:
            if k not in self._entries:
                if len(self._entries) >= self._CAP:
                    self._evict_dead_locked()
                self._entries[k] = e
        self._m_registered.labels(kind=kind).inc()
        return e

    def _evict_dead_locked(self):
        dead = [k for k, e in self._entries.items() if not e.alive]
        for k in dead[:max(len(self._entries) - self._CAP + 1,
                           len(dead) // 2)]:
            self._entries.pop(k, None)
        while len(self._entries) >= self._CAP:    # all alive: drop oldest
            self._entries.pop(next(iter(self._entries)))

    def note_compile(self, component: str, key, dt_ms: float):
        e = self._entries.get((component, key))
        if e is not None and e.compile_ms is None:
            e.compile_ms = dt_ms

    def note_runtime(self, component: str, key, dt_ms: float):
        """Steady-state pairing: one dict lookup + two adds.  The hot
        decode tick / train step calls this — nothing heavier belongs
        here."""
        e = self._entries.get((component, key))
        if e is not None:
            e.calls += 1
            e.runtime_ms += dt_ms

    # ---- deferred analysis --------------------------------------------
    def analyze(self, e: ExecEntry) -> bool:
        """AOT re-lower + compile from the stored shape structs and
        fold in XLA cost/memory analysis.  EXPLICIT and off the hot
        path: the compile it costs is served by the persistent cache as
        a deserialize, and a backend where any stage fails degrades the
        entry to timing-only (exec_analysis_failures_total counts it)
        instead of raising."""
        if e.analysis is not None:
            return True
        jitfn = e._jit_ref() if e._jit_ref is not None else None
        if jitfn is None or e._arg_shapes is None:
            self._m_failures.labels(stage="owner_released").inc()
            e.analysis_error = e.analysis_error or "owner released"
            return False
        try:
            compiled = jitfn.lower(
                *self._normalized_arg_shapes(e)).compile()
        except Exception as exc:
            self._m_failures.labels(stage="lower_compile").inc()
            e.analysis_error = (f"lower_compile: {type(exc).__name__}: "
                                f"{str(exc)[:200]}")
            return False
        from ..profiler import cost_stats, memory_stats
        cost = cost_stats(compiled)
        mem = memory_stats(compiled)
        out_sh: List[str] = []
        try:
            outs, _ = compiled.output_shardings \
                if isinstance(compiled.output_shardings, tuple) and \
                len(compiled.output_shardings) == 2 and \
                isinstance(compiled.output_shardings[1], dict) \
                else (compiled.output_shardings, None)
            import jax
            for sh in jax.tree_util.tree_leaves(outs)[:4]:
                s = str(sh)
                out_sh.append("single" if "SingleDevice" in s else s[:120])
        except Exception:
            pass
        e.analysis = {"cost": cost, "memory": mem,
                      "out_shardings": out_sh}
        # pod-scale serving (ISSUE 18): an entry that compiled against a
        # multi-device (sub)mesh folds in its collective traffic, split
        # per MESH AXIS — the tp/dp attribution bench --serve rows and
        # the doctor read.  Diagnostics only: any failure leaves the
        # cost/memory analysis intact and counts in the failure metric.
        shape = ((e.meta or {}).get("submesh") or {}).get("shape") or {}
        if any(int(n) > 1 for n in shape.values()):
            try:
                from ..utils import comm_stats as _comm
                e.analysis["collectives"] = _comm.analyze_compiled(
                    compiled,
                    axis_groups=_comm.axis_groups_from_shape(shape))
            except Exception:
                self._m_failures.labels(stage="collectives").inc()
        if not cost and not mem:
            # both analyses degraded (profiler counted each); entry
            # stays timing-only but records why
            e.analysis_error = e.analysis_error or \
                "cost_analysis/memory_analysis unavailable"
        return True

    def _normalized_arg_shapes(self, e: ExecEntry):
        """Arg structs safe to AOT-lower.  A first call mixes
        mesh-committed operands (params, cache) with host-resident ones
        (the first token batch), and ``lower()`` rejects the mixed
        device sets it would accept at runtime.  When the entry records
        a multi-device submesh, rebuild it and commit every leaf that
        does not already span it as REPLICATED on that submesh — which
        is where GSPMD puts those operands at runtime anyway."""
        sub = (e.meta or {}).get("submesh") or {}
        shape, dev_ids = sub.get("shape") or {}, sub.get("devices") or []
        if len(dev_ids) <= 1:
            return e._arg_shapes
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec
        try:
            by_id = {d.id: d for d in jax.devices()}
            mesh = Mesh(
                np.asarray([by_id[i] for i in dev_ids]).reshape(
                    [int(n) for n in shape.values()]),
                tuple(shape.keys()))
            repl = NamedSharding(mesh, PartitionSpec())
            dev_set = frozenset(dev_ids)

            def fix(leaf):
                if not isinstance(leaf, jax.ShapeDtypeStruct):
                    return leaf
                sh = leaf.sharding
                ids = {d.id for d in sh.device_set} if sh is not None \
                    else set()
                if ids == dev_set:
                    return leaf
                return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype,
                                            sharding=repl)
            return jax.tree_util.tree_map(fix, e._arg_shapes)
        except Exception:
            return e._arg_shapes

    def analyze_all(self, component: Optional[str] = None) -> int:
        """Analyze every (matching) entry; returns how many have
        analysis afterwards."""
        n = 0
        for e in self.entries(component):
            if self.analyze(e):
                n += 1
        return n

    def entries(self, component: Optional[str] = None) -> List[ExecEntry]:
        with self._lock:
            es = list(self._entries.values())
        if component is not None:
            es = [e for e in es if e.component == component]
        return es

    def clear(self):
        with self._lock:
            self._entries.clear()

    # ---- roofline snapshot --------------------------------------------
    def _entry_snapshot(self, e: ExecEntry, pf: float, pb: float,
                        nominal: bool) -> dict:
        mean_ms = (e.runtime_ms / e.calls) if e.calls else None
        d = {
            "component": e.component, "name": e.name, "kind": e.kind,
            "key": str(e.key), "calls": e.calls,
            "runtime_ms": round(e.runtime_ms, 3),
            "mean_ms": round(mean_ms, 4) if mean_ms is not None else None,
            "compile_ms": round(e.compile_ms, 2)
            if e.compile_ms is not None else None,
            "donate_argnums": list(e.donate_argnums),
            "in_shardings": e.in_shardings,
            "analyzed": e.analysis is not None,
            "peaks_nominal": nominal,
        }
        if e.meta:
            d["meta"] = dict(e.meta)
        if e.analysis_error:
            d["analysis_error"] = e.analysis_error
        if e.analysis is None:
            return d
        cost = e.analysis.get("cost") or {}
        mem = e.analysis.get("memory") or {}
        d["flops"] = cost.get("flops")
        d["bytes_accessed"] = cost.get("bytes_accessed")
        for fld in ("argument_bytes", "output_bytes", "temp_bytes",
                    "alias_bytes", "peak_bytes"):
            if fld in mem:
                d[fld] = int(mem[fld])
        if e.analysis.get("out_shardings"):
            d["out_shardings"] = e.analysis["out_shardings"]
        if e.analysis.get("collectives"):
            d["collectives"] = e.analysis["collectives"]
        flops = cost.get("flops") or 0.0
        nbytes = cost.get("bytes_accessed") or 0.0
        if mean_ms and mean_ms > 0:
            sec = mean_ms / 1e3
            if flops:
                ach_f = flops / sec
                d["achieved_flops_per_s"] = round(ach_f, 1)
                d["mfu"] = round(ach_f / pf, 6)
            if nbytes:
                ach_b = nbytes / sec
                d["achieved_hbm_gbps"] = round(ach_b / 1e9, 3)
                d["hbm_bw_frac"] = round(ach_b / pb, 6)
        if flops and nbytes:
            ai = flops / nbytes
            ridge = pf / pb
            d["arithmetic_intensity"] = round(ai, 3)
            d["ridge_ai"] = round(ridge, 3)
            d["bound"] = "compute" if ai >= ridge else "bandwidth"
            if mean_ms and mean_ms > 0:
                # the roof this executable can reach at ITS intensity
                roof = min(pf, ai * pb)
                d["roof_frac"] = round((flops / (mean_ms / 1e3)) / roof, 6)
        return d

    def snapshot(self, component: Optional[str] = None,
                 analyze: bool = False) -> dict:
        """JSON-safe observatory snapshot: per-executable records with
        roofline positions plus the MFU attribution (time share × gap
        to the 45% target).  ``analyze=True`` first runs the deferred
        XLA analyses (compiles — keep it off hot paths)."""
        if analyze:
            self.analyze_all(component)
        kind = device_kind()
        pf, f_nom = peak_flops(kind)
        pb, b_nom = peak_hbm_bytes_per_s(kind)
        nominal = f_nom or b_nom
        es = self.entries(component)
        rows = [self._entry_snapshot(e, pf, pb, nominal) for e in es]
        rows.sort(key=lambda r: -(r["runtime_ms"] or 0.0))
        total_rt = sum(r["runtime_ms"] for r in rows) or 0.0
        total_flops = 0.0
        for r in rows:
            if total_rt > 0:
                r["time_share"] = round(r["runtime_ms"] / total_rt, 4)
                mfu = r.get("mfu")
                if mfu is not None:
                    # this executable's charge against the gap to 45%:
                    # the wall-clock share it owns, scaled by how far
                    # below target it runs while owning it
                    r["mfu_weighted"] = round(r["time_share"] * mfu, 6)
                    r["gap_share"] = round(
                        r["time_share"] *
                        max(MFU_TARGET - mfu, 0.0) / MFU_TARGET, 4)
                    total_flops += (r.get("flops") or 0.0) * r["calls"]
        overall_mfu = (total_flops / (total_rt / 1e3) / pf) \
            if total_rt > 0 and total_flops else None
        out = {
            "device_kind": kind or "host",
            "peak_flops": pf,
            "peak_hbm_gbps": round(pb / 1e9, 1),
            "peaks_nominal": nominal,
            "mfu_target": MFU_TARGET,
            "executables": rows,
            "overall": {
                "runtime_ms": round(total_rt, 3),
                "analyzed": sum(1 for r in rows if r["analyzed"]),
                "registered": len(rows),
                "mfu": round(overall_mfu, 6)
                if overall_mfu is not None else None,
            },
        }
        self._export_gauges(rows)
        return out

    def _export_gauges(self, rows: List[dict]):
        """Mirror the observatory into Prometheus gauges (scrape-time
        cost only; never called from a hot loop)."""
        g_rt = _metrics.gauge("exec_runtime_ms_total",
                              "cumulative steady-state wall ms",
                              labels=("component", "exec"))
        g_calls = _metrics.gauge("exec_calls_total", "steady-state calls",
                                 labels=("component", "exec"))
        g_flops = _metrics.gauge("exec_flops", "XLA cost-analysis flops",
                                 labels=("component", "exec"))
        g_peak = _metrics.gauge("exec_peak_bytes",
                                "arg+out+temp-alias bytes",
                                labels=("component", "exec"))
        g_mfu = _metrics.gauge("exec_mfu", "achieved/peak FLOPs",
                               labels=("component", "exec"))
        for r in rows:
            lbl = dict(component=r["component"], exec=r["name"])
            g_rt.labels(**lbl).set(r["runtime_ms"])
            g_calls.labels(**lbl).set(r["calls"])
            if r.get("flops") is not None:
                g_flops.labels(**lbl).set(r["flops"])
            if r.get("peak_bytes") is not None:
                g_peak.labels(**lbl).set(r["peak_bytes"])
            if r.get("mfu") is not None:
                g_mfu.labels(**lbl).set(r["mfu"])

    def profile(self, component: str) -> Optional[dict]:
        """Per-kind roofline digest for one component — what
        ``trainer.stats['exec_profile']`` / ``engine.stats
        ['exec_profile']`` / bench rows carry.  Pure dict math over
        ALREADY-analyzed entries (None when nothing is analyzed yet):
        reading stats never compiles."""
        if not any(e.analysis is not None
                   for e in self.entries(component)):
            return None
        return profile_from_snapshot(self.snapshot(component))


def profile_from_snapshot(snap: dict) -> Optional[dict]:
    """Build the per-kind exec_profile digest the doctor rules read
    from a registry snapshot — live (``ExecRegistry.profile``) or
    offline (the report CLI reloading a snapshot file).  ONE
    implementation so the two can never drift: highest-runtime analyzed
    row per kind, plus the ``_overall``/``_peaks`` context."""
    prof: Dict[str, dict] = {}
    for r in snap.get("executables") or []:
        if not r.get("analyzed") or r.get("kind") is None:
            continue
        cur = prof.get(r["kind"])
        if cur is None or (r.get("runtime_ms") or 0) > \
                (cur.get("runtime_ms") or 0):
            prof[r["kind"]] = r
    if not prof:
        return None
    prof["_overall"] = snap.get("overall")
    prof["_peaks"] = {"device_kind": snap.get("device_kind"),
                      "peak_flops": snap.get("peak_flops"),
                      "peak_hbm_gbps": snap.get("peak_hbm_gbps"),
                      "peaks_nominal": snap.get("peaks_nominal")}
    return prof


def _default_name(key, kind: str) -> str:
    if isinstance(key, tuple):
        parts = [str(p) for p in key if not (isinstance(p, int) and
                                             p == 0)]
        return "/".join(parts) if parts else kind
    return str(key)


# ---------------------------------------------------------------------------
# HBM ledger
# ---------------------------------------------------------------------------
class HBMLedger:
    """Live device-memory accounting.  ``track(owner, category, name,
    nbytes)`` records one resident allocation (params, optimizer state,
    KV pool, draft cache) under a WEAKREF to its owner — a retired
    engine's pool drops out of the ledger when the engine is collected.
    ``snapshot()`` folds in the worst per-executable temp bytes the
    exec registry analyzed and reports headroom against device
    capacity."""

    def __init__(self):
        self._tracked: Dict[Tuple[str, str], dict] = {}
        self._lock = threading.Lock()

    def track(self, owner, category: str, name: str, nbytes: int,
              **meta):
        rec = {"category": category, "name": name, "bytes": int(nbytes),
               "meta": meta or None,
               "ref": weakref.ref(owner) if owner is not None else None}
        with self._lock:
            self._tracked[(category, name)] = rec

    def untrack(self, category: str, name: str):
        with self._lock:
            self._tracked.pop((category, name), None)

    def clear(self):
        with self._lock:
            self._tracked.clear()

    def _live(self) -> List[dict]:
        with self._lock:
            recs = list(self._tracked.items())
        out = []
        dead = []
        for key, r in recs:
            if r["ref"] is not None and r["ref"]() is None:
                dead.append(key)
                continue
            out.append(r)
        if dead:
            with self._lock:
                for key in dead:
                    self._tracked.pop(key, None)
        return out

    def snapshot(self, exec_registry: Optional[ExecRegistry] = None
                 ) -> dict:
        live = self._live()
        by_cat: Dict[str, int] = {}
        for r in live:
            by_cat[r["category"]] = by_cat.get(r["category"], 0) + \
                r["bytes"]
        live_bytes = sum(by_cat.values())
        reg = exec_registry if exec_registry is not None else registry()
        exec_temp = 0
        exec_peak_name = None
        for e in reg.entries():
            mem = (e.analysis or {}).get("memory") or {}
            t = int(mem.get("temp_bytes", 0) or 0)
            if t > exec_temp:
                exec_temp, exec_peak_name = t, f"{e.component}:{e.name}"
        cap = device_hbm_capacity()
        out = {
            "capacity_bytes": cap,
            "tracked_bytes": live_bytes,
            "by_category": by_cat,
            "tracked": [{"category": r["category"], "name": r["name"],
                         "bytes": r["bytes"]} for r in live],
            "exec_temp_bytes": exec_temp,
            "exec_temp_worst": exec_peak_name,
        }
        if cap:
            headroom = cap - live_bytes - exec_temp
            out["headroom_bytes"] = int(headroom)
            out["headroom_frac"] = round(headroom / cap, 4)
            out["oom_risk"] = headroom / cap < OOM_HEADROOM_MIN
        else:
            out["headroom_bytes"] = None
            out["headroom_frac"] = None
            out["oom_risk"] = None
        g = _metrics.gauge("hbm_tracked_bytes",
                           "ledger-resident device bytes",
                           labels=("category",))
        for cat, b in by_cat.items():
            g.labels(category=cat).set(b)
        if cap:
            _metrics.gauge("hbm_capacity_bytes",
                           "device memory capacity").set(cap)
            _metrics.gauge("hbm_headroom_bytes",
                           "capacity - tracked - worst exec temp").set(
                out["headroom_bytes"])
        return out


_REGISTRY = ExecRegistry()
_LEDGER = HBMLedger()


def registry() -> ExecRegistry:
    return _REGISTRY


def ledger() -> HBMLedger:
    return _LEDGER


def register(component: str, key, kind: str, **kw):
    return _REGISTRY.register(component, key, kind, **kw)


def note_runtime(component: str, key, dt_ms: float):
    _REGISTRY.note_runtime(component, key, dt_ms)


def analyze_all(component: Optional[str] = None) -> int:
    return _REGISTRY.analyze_all(component)


def profile(component: str) -> Optional[dict]:
    return _REGISTRY.profile(component)


def snapshot(component: Optional[str] = None, analyze: bool = False
             ) -> dict:
    return _REGISTRY.snapshot(component, analyze=analyze)


def track_bytes(owner, category: str, name: str, nbytes: int, **meta):
    _LEDGER.track(owner, category, name, nbytes, **meta)
