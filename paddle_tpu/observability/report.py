"""Human-readable observatory report.

``python -m paddle_tpu.observability.report`` renders the executable
registry + HBM ledger + doctor verdicts as text tables — from a LIVE
process is pointless (the process would have to be this one), so the
CLI is an OFFLINE reader: point it at a snapshot JSONL file
(``observability.write_snapshot``), a flight-recorder bundle dir, or a
``BENCH_rows.jsonl``; with no arguments it tries the
``PADDLE_TPU_METRICS`` path and then the newest flightrec bundle.  No
accelerator is required — everything renders from the JSON.

    python -m paddle_tpu.observability.report --snapshot metrics.jsonl
    python -m paddle_tpu.observability.report --bundle \
        /tmp/paddle_tpu_flightrec/flightrec-123-001-stall
    python -m paddle_tpu.observability.report --rows BENCH_rows.jsonl

Exit codes: 0 rendered something, 2 nothing to render.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

__all__ = ["render_executables", "render_hbm", "render_doctor",
           "render_tuning", "render_snapshot", "load_snapshot_file",
           "main"]


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return "-"


def _fmt(v, nd=2) -> str:
    if v is None:
        return "-"
    if isinstance(v, bool):
        return "y" if v else "n"
    if isinstance(v, float):
        return f"{v:.{nd}f}"
    return str(v)


def _table(headers: List[str], rows: List[List[str]]) -> str:
    widths = [len(h) for h in headers]
    for r in rows:
        for i, c in enumerate(r):
            widths[i] = max(widths[i], len(c))
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*headers), fmt.format(*["-" * w for w in widths])]
    lines += [fmt.format(*r) for r in rows]
    return "\n".join(lines)


def render_executables(execsnap: Optional[dict]) -> str:
    """The registry table: one row per executable with timings, XLA
    cost/memory figures and roofline position."""
    if not execsnap or not execsnap.get("executables"):
        return "executables: none registered"
    head = (f"executables on {execsnap.get('device_kind', '?')} "
            f"(peak {execsnap.get('peak_flops', 0) / 1e12:.1f} TFLOP/s, "
            f"{execsnap.get('peak_hbm_gbps', 0):.0f} GB/s HBM"
            + (", NOMINAL host peaks" if execsnap.get("peaks_nominal")
               else "") + ")")
    rows = []
    for r in execsnap["executables"]:
        flops = r.get("flops")
        rows.append([
            r.get("component", "?"), r.get("name", "?"),
            r.get("kind", "?"), str(r.get("calls", 0)),
            _fmt(r.get("mean_ms"), 3),
            f"{flops / 1e9:.2f}" if flops else "-",
            _fmt_bytes(r.get("bytes_accessed")),
            _fmt_bytes(r.get("peak_bytes")),
            _fmt(r.get("arithmetic_intensity"), 1),
            r.get("bound", "-") or "-",
            f"{r['mfu'] * 100:.2f}%" if r.get("mfu") is not None else "-",
            f"{r['hbm_bw_frac'] * 100:.1f}%"
            if r.get("hbm_bw_frac") is not None else "-",
            f"{r['roof_frac'] * 100:.1f}%"
            if r.get("roof_frac") is not None else "-",
            _fmt(r.get("time_share")),
            _fmt(r.get("gap_share")),
            ("!" + r["analysis_error"][:40]) if r.get("analysis_error")
            else "",
        ])
    table = _table(
        ["component", "exec", "kind", "calls", "mean_ms", "GFLOP",
         "bytes", "peak_mem", "AI", "bound", "MFU", "BW%", "roof%",
         "t_share", "gap45%", "notes"], rows)
    overall = execsnap.get("overall") or {}
    tail = (f"analyzed {overall.get('analyzed', 0)}/"
            f"{overall.get('registered', 0)} executables, "
            f"{overall.get('runtime_ms', 0):.1f}ms steady-state wall")
    if overall.get("mfu") is not None:
        tail += (f", overall MFU {overall['mfu'] * 100:.2f}% "
                 f"(target {execsnap.get('mfu_target', 0.45) * 100:.0f}%)")
    return f"{head}\n{table}\n{tail}"


def render_hbm(h: Optional[dict]) -> str:
    if not h:
        return "hbm ledger: empty"
    rows = [[t.get("category", "?"), t.get("name", "?"),
             _fmt_bytes(t.get("bytes"))]
            for t in (h.get("tracked") or [])]
    table = _table(["category", "name", "bytes"], rows) if rows \
        else "(nothing tracked)"
    tail = (f"tracked {_fmt_bytes(h.get('tracked_bytes'))}, worst exec "
            f"temp {_fmt_bytes(h.get('exec_temp_bytes'))}"
            + (f" ({h['exec_temp_worst']})" if h.get("exec_temp_worst")
               else ""))
    cap = h.get("capacity_bytes")
    if cap:
        tail += (f", capacity {_fmt_bytes(cap)}, headroom "
                 f"{_fmt_bytes(h.get('headroom_bytes'))} "
                 f"({(h.get('headroom_frac') or 0) * 100:.1f}%)")
        if h.get("oom_risk"):
            tail += "  ** OOM RISK **"
    else:
        tail += ", capacity unknown (no device memory_stats; set " \
                "PADDLE_TPU_HBM_BYTES)"
    return f"hbm ledger\n{table}\n{tail}"


def _fmt_action(a) -> str:
    """Compact one-cell form of a verdict's structured action:
    ``param in [candidates]`` plus the table op / env when set; '-' for
    behavioral advice (no machine-turnable axis)."""
    if not isinstance(a, dict) or not a.get("param"):
        return "-"
    s = a["param"]
    cands = a.get("candidates")
    if cands:
        s += " in [" + ",".join(_fmt(c) for c in cands) + "]"
    if a.get("op"):
        s += f" ->{a['op']}"
    return s


def render_doctor(verdicts) -> str:
    if not verdicts:
        return "doctor: no bottleneck found"
    rows = []
    for v in verdicts:
        ev = v.get("evidence") or {}
        ev_s = ", ".join(f"{k}={ev[k]}" for k in list(ev)[:4])
        rows.append([v.get("bottleneck", "?"),
                     _fmt(v.get("score")), ev_s[:60],
                     (v.get("knob") or "")[:70],
                     _fmt_action(v.get("action"))[:46]])
    return "doctor verdicts\n" + _table(
        ["bottleneck", "score", "evidence", "knob", "action"], rows)


def render_tuning() -> str:
    """The unified tuning table with provenance (ISSUE 16): every op's
    entries from utils.tuning plus who committed each one (source /
    run / measured improvement) — winners are auditable."""
    from ..utils import tuning as _tuning
    ops = _tuning.all_entries()
    rows = []
    for op in sorted(ops):
        for key in sorted(ops[op]):
            meta = _tuning.provenance(op, key) or {}
            imp = meta.get("improvement")
            rows.append([
                op, "|".join(key), json.dumps(ops[op][key])[:40],
                meta.get("source", "-"), meta.get("run", "-"),
                f"+{imp * 100:.2f}%" if isinstance(imp, (int, float))
                else "-"])
    if not rows:
        return (f"tuning table: empty "
                f"({_tuning.tuning_path() or 'persistence off'})")
    return (f"tuning table ({_tuning.tuning_path() or 'in-process'})\n"
            + _table(["op", "key", "value", "source", "run",
                      "improvement"], rows))


def render_snapshot(rec: dict, doctor_rows: Optional[list] = None) -> str:
    """Render one full snapshot record ({'metrics', 'executables',
    'hbm', ...}) — the function the tests round-trip through."""
    from . import doctor as _doctor
    from .exec_registry import profile_from_snapshot
    execsnap = rec.get("executables")
    h = rec.get("hbm")
    parts = [render_executables(execsnap), "", render_hbm(h)]
    # fresh roofline/ledger verdicts derived from the snapshot itself —
    # the SAME digest builder the live stats surfaces use
    stats = {"hbm": h}
    prof = profile_from_snapshot(execsnap or {})
    if prof:
        stats["exec_profile"] = prof
        stats["decode_steps"] = max(
            (r.get("calls", 0) for k, r in prof.items()
             if k in ("decode", "megakernel_decode", "spec_verify")),
            default=0)
    parts += ["", render_doctor(_doctor.diagnose(stats))]
    if doctor_rows:
        parts += ["", "latest bench-row doctor:",
                  render_doctor(doctor_rows)]
    ts = rec.get("ts")
    if ts:
        parts.insert(0, f"snapshot ts={ts}")
    return "\n".join(parts)


def load_snapshot_file(path: str) -> Optional[dict]:
    """Last parseable line of a snapshot JSONL file."""
    rec = None
    try:
        with open(path, errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
    except OSError:
        return None
    return rec if isinstance(rec, dict) else None


def _load_bundle(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, "bundle.json"),
                  errors="replace") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _latest_rows_doctor(path: str) -> Optional[list]:
    last = None
    try:
        with open(path, errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and isinstance(
                        rec.get("doctor"), list):
                    last = rec["doctor"]
    except OSError:
        return None
    return last


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.report",
        description="Render the executable observatory (registry + HBM "
                    "ledger + doctor) from a snapshot file, flightrec "
                    "bundle, or bench rows file — offline, no device.")
    ap.add_argument("--snapshot", help="snapshot JSONL "
                    "(observability.write_snapshot output)")
    ap.add_argument("--bundle", help="flight-recorder bundle directory")
    ap.add_argument("--rows", help="BENCH_rows.jsonl (renders the "
                    "latest row's doctor verdicts alongside)")
    ap.add_argument("--tuning", action="store_true",
                    help="print the unified tuning table with "
                         "provenance (source/run/improvement)")
    args = ap.parse_args(argv)

    if args.tuning:
        print("== paddle_tpu tuning table ==")
        print(render_tuning())
        if not (args.snapshot or args.bundle or args.rows):
            return 0

    rec = None
    source = None
    if args.snapshot:
        rec = load_snapshot_file(args.snapshot)
        source = args.snapshot
        if rec is None:
            print(f"report: no parseable snapshot line in "
                  f"{args.snapshot}", file=sys.stderr)
            return 2
    elif args.bundle:
        rec = _load_bundle(args.bundle)
        source = args.bundle
        if rec is None:
            print(f"report: {args.bundle} is not a readable bundle",
                  file=sys.stderr)
            return 2
    else:
        env = os.environ.get("PADDLE_TPU_METRICS", "")
        if env not in ("", "0", "1") and os.path.exists(env):
            rec = load_snapshot_file(env)
            source = env
        if rec is None:
            from . import flightrec as _fr
            bundles = _fr.find_bundles()
            if bundles:
                rec = _load_bundle(bundles[-1])
                source = bundles[-1]
    doctor_rows = _latest_rows_doctor(args.rows) if args.rows else None
    if rec is None and doctor_rows is not None:
        # --rows alone: render the latest bench row's doctor verdicts
        # (the rows file carries no registry snapshot, so that is the
        # whole report — still a report, not an error)
        print(f"== paddle_tpu observatory report ({args.rows}) ==")
        print("latest bench-row doctor:")
        print(render_doctor(doctor_rows))
        return 0
    if rec is None:
        print("report: nothing to render — pass --snapshot/--bundle/"
              "--rows (see --help)", file=sys.stderr)
        return 2

    print(f"== paddle_tpu observatory report ({source}) ==")
    if rec.get("reason"):
        print(f"flightrec reason: {rec['reason']}")
    print(render_snapshot(rec, doctor_rows=doctor_rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
