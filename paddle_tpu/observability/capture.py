"""Profile capture control: window a device trace over a step range.

The reference framework gates its profiler with an explicit
``EnableProfiler``/``DisableProfiler`` state machine (profiler.h:210);
the TPU-native equivalent is ``jax.profiler.start_trace``/``stop_trace``
writing a TensorBoard/Perfetto capture.  What neither gives you is
CONTROL tied to the training/serving clock: "capture steps 20..25" —
after warmup, long enough to see steady state, short enough to load in
a UI.

``ProfileWindow`` is that control.  ``PADDLE_TPU_PROFILE=start:stop``
(optionally ``start:stop:logdir``) arms a window; ``SpmdTrainer`` ticks
it per train step and ``InferenceEngine`` per decode tick, so the same
knob captures either.  When the env is unset ``from_env`` returns None
and the entry points hold a literal None — the steady-state cost of the
feature is one ``is not None`` check per step, no allocation, no call.

Host spans recorded while a capture is active nest inside the device
trace via the ``jax.profiler.TraceAnnotation`` half of RecordEvent; the
chrome-trace export (observability.spans) is independent of captures and
works with no device profiler at all.
"""
from __future__ import annotations

import os
import warnings
from typing import Optional

__all__ = ["ProfileWindow", "parse_profile_spec"]

_DEFAULT_LOGDIR = "/tmp/paddle_tpu_profile"


def parse_profile_spec(spec: str):
    """``"start:stop[:logdir]"`` -> (start, stop, logdir).  Raises
    ValueError on nonsense (stop <= start, non-ints) — a mistyped env
    should fail loudly at startup, not silently never capture."""
    parts = spec.split(":", 2)
    if len(parts) < 2:
        raise ValueError(
            f"PADDLE_TPU_PROFILE must be 'start:stop[:logdir]', "
            f"got {spec!r}")
    start, stop = int(parts[0]), int(parts[1])
    if stop <= start or start < 0:
        raise ValueError(
            f"PADDLE_TPU_PROFILE window [{start}:{stop}) is empty or "
            f"negative")
    logdir = parts[2] if len(parts) > 2 and parts[2] else _DEFAULT_LOGDIR
    return start, stop, logdir


class ProfileWindow:
    """Capture device+host profile over steps [start, stop).

    ``on_step(n)`` is called with the step/tick counter AFTER the work
    of step n-1 (i.e. before step n runs): the trace starts when n ==
    start and stops when n >= stop.  One window per process lifetime —
    re-arming needs a new object (matching jax's one-trace-at-a-time
    profiler)."""

    def __init__(self, start: int, stop: int,
                 log_dir: str = _DEFAULT_LOGDIR, kind: str = "train"):
        self.start = int(start)
        self.stop = int(stop)
        self.log_dir = log_dir
        self.kind = kind
        self.active = False
        self.done = False
        self.trace_dir: Optional[str] = None

    @classmethod
    def from_env(cls, kind: str = "train",
                 env: str = "PADDLE_TPU_PROFILE"
                 ) -> Optional["ProfileWindow"]:
        spec = os.environ.get(env, "").strip()
        if not spec:
            return None
        start, stop, logdir = parse_profile_spec(spec)
        return cls(start, stop, log_dir=os.path.join(logdir, kind),
                   kind=kind)

    def on_step(self, step: int):
        """Advance the window clock.  Never raises: a broken profiler
        backend must not take the step loop down (warn once, disarm)."""
        if self.done:
            return
        if self.active:
            if step >= self.stop:
                self._stop()
        elif step >= self.start:
            if step >= self.stop:       # window already behind us
                self.done = True
                return
            self._start()

    def _start(self):
        from .. import profiler as _prof
        try:
            self.trace_dir = _prof.start_profiler(self.log_dir)
            self.active = True
        except Exception as e:          # pragma: no cover - backend dep
            warnings.warn(f"PADDLE_TPU_PROFILE capture failed to start "
                          f"({type(e).__name__}: {e}); disarmed")
            self.done = True

    def _stop(self):
        from .. import profiler as _prof
        try:
            _prof.stop_profiler()
        except Exception as e:          # pragma: no cover - backend dep
            warnings.warn(f"PADDLE_TPU_PROFILE capture failed to stop "
                          f"({type(e).__name__}: {e})")
        self.active = False
        self.done = True

    def close(self):
        """Force-stop an open capture (drain/teardown path)."""
        if self.active:
            self._stop()
