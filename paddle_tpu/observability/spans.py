"""Structured span tracing -> Chrome-trace / Perfetto JSON.

``profiler.RecordEvent`` (the reference ``platform/profiler.h:127`` RAII
marker) annotates the DEVICE timeline via
``jax.profiler.TraceAnnotation``; this module is its host-side twin: the
same enter/exit pairs also land in a process-wide event buffer as
structured spans, which export as Chrome-trace JSON (``chrome://tracing``
/ Perfetto's ``ui.perfetto.dev`` open it directly — the reference
``device_tracer.h:43`` CUPTI→chrome-trace path, minus CUPTI).

Tracks (Chrome-trace pid/tid):

- ``pid=1`` "host": named phase spans — train step phases
  (data_wait/h2d/dispatch/sync), decode ticks, prefill calls.  ``tid``
  is the emitting thread.
- ``pid=2`` "requests": one track PER REQUEST (``tid=rid``) holding its
  lifecycle — ``queued`` → ``prefill`` → ``decode`` — plus instant
  events for preemptions and per-tick speculative accept counts.

The contract the overhead tests enforce: tracing costs nothing when
off.  Every instrumentation site guards on ``tracer().active`` (one
attribute read, no call, no allocation), and recording itself is
timestamp arithmetic + ``list.append`` — no host syncs, no jax calls,
so a traced decode loop stays zero-recompile and one-sync-per-tick.

Knobs: ``PADDLE_TPU_SPANS=1`` arms the tracer at import;
``PADDLE_TPU_SPANS=<path>.json`` also names the default export path.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["SpanTracer", "tracer", "span", "export_chrome_trace",
           "validate_chrome_trace", "PID_HOST", "PID_REQUESTS"]

PID_HOST = 1
PID_REQUESTS = 2

_DEFAULT_CAPACITY = 250_000


class SpanTracer:
    """Bounded in-memory span buffer.  ``active`` is the hot-path gate:
    instrumentation reads it before building any event."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        self.active = False
        self.capacity = int(capacity)
        self._events: List[dict] = []
        self.dropped = 0
        # one shared epoch so spans from every thread/component align;
        # perf_counter()/perf_counter_ns() share a clock
        self._t0_ns = time.perf_counter_ns()

    # ---- lifecycle ----------------------------------------------------
    def start(self):
        self.active = True
        return self

    def stop(self):
        self.active = False
        return self

    def clear(self):
        self._events = []
        self.dropped = 0

    def __len__(self):
        return len(self._events)

    # ---- time ---------------------------------------------------------
    def now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0_ns) / 1e3

    def to_us(self, perf_counter_s: float) -> float:
        """Map a ``time.perf_counter()`` float (the repo's ubiquitous
        timestamp currency — Request.t_enqueue etc.) onto the trace
        clock."""
        return max(perf_counter_s * 1e6 - self._t0_ns / 1e3, 0.0)

    # ---- recording (host-side arithmetic only) ------------------------
    def _push(self, ev: dict):
        if len(self._events) >= self.capacity:
            self.dropped += 1
            return
        self._events.append(ev)     # list.append is GIL-atomic

    def complete(self, name: str, ts_us: float, dur_us: float,
                 pid: int = PID_HOST, tid: Optional[int] = None,
                 cat: str = "host", args: Optional[dict] = None):
        """One finished span ('X' event)."""
        ev = {"name": name, "ph": "X", "ts": round(ts_us, 3),
              "dur": round(max(dur_us, 0.0), 3), "pid": pid,
              "tid": tid if tid is not None else threading.get_ident()
              % 1_000_000, "cat": cat}
        if args:
            ev["args"] = args
        self._push(ev)

    def instant(self, name: str, pid: int = PID_HOST,
                tid: Optional[int] = None, cat: str = "host",
                args: Optional[dict] = None,
                ts_us: Optional[float] = None):
        ev = {"name": name, "ph": "i", "s": "t",
              "ts": round(self.now_us() if ts_us is None else ts_us, 3),
              "pid": pid,
              "tid": tid if tid is not None else threading.get_ident()
              % 1_000_000, "cat": cat}
        if args:
            ev["args"] = args
        self._push(ev)

    # ---- export -------------------------------------------------------
    def chrome_trace(self) -> dict:
        """The Chrome-trace document (Perfetto-compatible)."""
        meta = [
            {"name": "process_name", "ph": "M", "pid": PID_HOST, "tid": 0,
             "args": {"name": "paddle_tpu host"}},
            {"name": "process_name", "ph": "M", "pid": PID_REQUESTS,
             "tid": 0, "args": {"name": "requests"}},
        ]
        # label each request track by its rid
        rids = sorted({ev["tid"] for ev in self._events
                       if ev["pid"] == PID_REQUESTS})
        meta += [{"name": "thread_name", "ph": "M", "pid": PID_REQUESTS,
                  "tid": rid, "args": {"name": f"request {rid}"}}
                 for rid in rids]
        return {"traceEvents": meta + list(self._events),
                "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def export(self, path: str) -> str:
        """Write the Chrome-trace JSON atomically (fs.open_for_write)."""
        from ..framework.fs import open_for_write
        with open_for_write(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path


_TRACER = SpanTracer()
if os.environ.get("PADDLE_TPU_SPANS", "") not in ("", "0"):
    _TRACER.start()


def tracer() -> SpanTracer:
    return _TRACER


def default_export_path() -> Optional[str]:
    env = os.environ.get("PADDLE_TPU_SPANS", "")
    return env if env not in ("", "0", "1") else None


class span:
    """Context manager recording one host span (when the tracer is
    active).  For hot loops prefer guarding on ``tracer().active`` and
    calling ``complete`` with timestamps you already have."""

    __slots__ = ("name", "cat", "args", "_t0")

    def __init__(self, name: str, cat: str = "host",
                 args: Optional[dict] = None):
        self.name = name
        self.cat = cat
        self.args = args
        self._t0 = 0.0

    def __enter__(self):
        if _TRACER.active:
            self._t0 = _TRACER.now_us()
        return self

    def __exit__(self, *exc):
        if _TRACER.active:
            now = _TRACER.now_us()
            _TRACER.complete(self.name, self._t0, now - self._t0,
                             cat=self.cat, args=self.args)
        return False


def export_chrome_trace(path: Optional[str] = None) -> Optional[str]:
    """Export the global tracer's buffer; default path from
    ``PADDLE_TPU_SPANS=<path>``.  Returns the path or None when there is
    nowhere to write."""
    path = path or default_export_path()
    if not path:
        return None
    return _TRACER.export(path)


def validate_chrome_trace(doc) -> int:
    """Structural validation of a Chrome-trace document (the smoke's
    'the timeline actually loads' check): every event needs name/ph/pid
    /tid, 'X' events need numeric ts+dur.  Returns the event count;
    raises ValueError on the first malformed event."""
    if isinstance(doc, (str, bytes)):
        doc = json.loads(doc)
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        raise ValueError("traceEvents missing or not a list")
    for i, ev in enumerate(events):
        for k in ("name", "ph", "pid", "tid"):
            if k not in ev:
                raise ValueError(f"event {i} missing {k!r}: {ev}")
        if ev["ph"] == "X":
            if not isinstance(ev.get("ts"), (int, float)) or \
                    not isinstance(ev.get("dur"), (int, float)):
                raise ValueError(f"event {i} has non-numeric ts/dur: {ev}")
            if ev["dur"] < 0 or ev["ts"] < 0:
                raise ValueError(f"event {i} has negative ts/dur: {ev}")
    return len(events)
