"""Unified telemetry layer (reference: the platform-layer profiler /
monitor registry — PAPER.md §1 layer 0).

One sink, four capabilities, every entry point feeds it:

- **metrics** — process-wide registry (counters/gauges/histograms with
  labels, lock-free hot path), Prometheus text exposition + round-trip
  parser, atomic JSONL snapshots.  Fed by SpmdTrainer / GPipeTrainer
  step loops, the serving engine's decode tick, the paged allocator,
  the router, checkpoint save/restore, the compile/trace and host-sync
  counters, and the load harness.
- **spans** — structured host spans (train step phases, per-request
  serving lifecycle) exported as Chrome-trace/Perfetto JSON, nested
  inside device captures via jax.profiler.TraceAnnotation.
- **capture** — ``PADDLE_TPU_PROFILE=start:stop`` windows a
  jax.profiler device trace over a step/tick range with zero
  steady-state overhead.
- **slo** — fleet aggregation over engine replicas + a rolling SLO
  monitor (threshold breaches, regression vs BENCH_rows.jsonl).
- **flightrec** — always-on bounded black box: recent step/tick ring +
  event log dumped as an atomic post-mortem bundle (JSON + Chrome
  trace) on unhandled exception, SIGTERM, rollback, fault kill, stall.
- **watchdog** — monitor thread fed per-step/per-tick heartbeats; a
  no-progress stall dumps all-thread stacks + a flightrec bundle.
  Plus fleet straggler detection (tick-time skew vs median).
- **doctor** — rule-based bottleneck attribution over the stats the
  entry points already emit: ranked ``[{bottleneck, evidence, knob}]``
  verdicts in ``trainer.stats['doctor']`` / ``engine.stats['doctor']``
  / bench rows / loadgen reports.

Invariants (proven in tests/test_telemetry.py): telemetry-on adds zero
host syncs per decode tick and keeps the decode loop zero-recompile;
telemetry-off adds no per-step allocations.
"""
from . import doctor
from . import exec_registry
from . import flightrec
from . import metrics
from . import spans
from . import watchdog
from .capture import ProfileWindow, parse_profile_spec
from .doctor import diagnose
from .exec_registry import ExecRegistry, HBMLedger
from .flightrec import FlightRecorder
from .metrics import counter, gauge, histogram, parse_exposition, registry
from .slo import FleetAggregator, SLOMonitor, load_bench_baseline
from .spans import (export_chrome_trace, span, tracer,
                    validate_chrome_trace)
from .watchdog import Watchdog, detect_stragglers

__all__ = [
    "metrics", "spans", "counter", "gauge", "histogram", "registry",
    "snapshot", "write_snapshot", "parse_exposition",
    "span", "tracer", "export_chrome_trace", "validate_chrome_trace",
    "ProfileWindow", "parse_profile_spec",
    "FleetAggregator", "SLOMonitor", "load_bench_baseline",
    "flightrec", "FlightRecorder", "watchdog", "Watchdog",
    "detect_stragglers", "doctor", "diagnose",
    "exec_registry", "ExecRegistry", "HBMLedger",
]


def snapshot() -> dict:
    """THE one-call answer: every registered train/serve/fleet metric,
    the executable observatory (per-executable cost/roofline records —
    whatever analyses have run; reading never compiles), the HBM
    ledger, and tracer state — all JSON-safe."""
    return {
        "metrics": metrics.snapshot(),
        "executables": exec_registry.snapshot(),
        "hbm": exec_registry.ledger().snapshot(),
        "spans": {"buffered": len(spans.tracer()),
                  "dropped": spans.tracer().dropped,
                  "active": spans.tracer().active},
    }


def write_snapshot(path=None, extra=None):
    """Append one FULL snapshot line (metrics + executables + hbm) to
    the JSONL history file — same atomic-rename + line/size rotation as
    metrics.write_snapshot, which this wraps.  The report CLI
    (``python -m paddle_tpu.observability.report``) renders these files
    offline."""
    full = {"executables": exec_registry.snapshot(),
            "hbm": exec_registry.ledger().snapshot()}
    if extra:
        full.update(extra)
    return metrics.write_snapshot(path, extra=full)
