"""Process-wide metrics registry: counters, gauges, histograms.

The repo grew five disconnected stat surfaces (``SpmdTrainer.stats``,
``GPipeTrainer.stats``, ``engine.stats``, ``comm_stats``,
``compile_counter``) that each invented their own dict shape and none of
which a scraper could read.  This module is the one sink they all feed
— the reference framework's monitor.h ``STAT_ADD`` registry recast for a
Python host process:

- **Counter** (monotone), **Gauge** (set/any direction), **Histogram**
  (fixed buckets + sum + count), each with optional label dimensions.
- The hot path is LOCK-FREE for the common single-writer case:
  ``metric.labels(...)`` returns a cached child object whose
  ``inc``/``set``/``observe`` are plain attribute arithmetic (no lock
  acquisition, no dict lookup when the caller binds the child once).
  ``+=`` is NOT atomic across threads — a child incremented from
  MULTIPLE threads needs external synchronization (the host-sync and
  compile counters update their mirrors under the locks they already
  hold; per-engine children are single-writer by the engine's own
  one-thread contract).  Locks guard registration and label-child
  creation — cold paths.
- Children live for the process lifetime (standard Prometheus
  semantics): a label value minted per object (``engine="e3"``,
  ``pool="p7"``) keeps exporting its last value after the object dies.
  Keep label cardinality small and monotone ids short-lived processes
  only.
- Exposition: Prometheus text format (``exposition()``) plus a
  round-trip parser (``parse_exposition``) so the bench smoke can PROVE
  the output scrapes, and an atomic JSONL snapshot writer riding
  ``framework.fs.open_for_write`` (fsync + tmp + rename — a crashed
  snapshot never truncates the history file).

``PADDLE_TPU_METRICS=0`` disables the registry: every factory returns a
shared null metric whose children are no-ops, so the disabled path costs
one attribute call and allocates nothing per step.
"""
from __future__ import annotations

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["Registry", "registry", "counter", "gauge", "histogram",
           "snapshot", "write_snapshot", "parse_exposition",
           "metrics_enabled", "DEFAULT_MS_BUCKETS"]

# latency-in-milliseconds buckets: TTFT/step-time spreads from sub-ms
# CPU smokes to multi-second TPU prefills all land on a usable bucket
DEFAULT_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def metrics_enabled() -> bool:
    return os.environ.get("PADDLE_TPU_METRICS", "1") != "0"


class _CounterChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, n: float = 1.0):
        self.value += n


class _GaugeChild:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float):
        self.value = v

    def inc(self, n: float = 1.0):
        self.value += n

    def dec(self, n: float = 1.0):
        self.value -= n


class _HistChild:
    __slots__ = ("bounds", "counts", "sum", "count")

    def __init__(self, bounds: Sequence[float]):
        self.bounds = tuple(bounds)
        # one slot per bound + the +Inf overflow slot
        self.counts = [0] * (len(self.bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float):
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.sum += v
        self.count += 1

    def percentile(self, q: float) -> Optional[float]:
        """Bucket-resolution percentile (upper bound of the bucket the
        q-quantile falls in) — what a scraper would compute; good enough
        for SLO breach detection, not a substitute for raw records."""
        if not self.count:
            return None
        target = q / 100.0 * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.bounds[i] if i < len(self.bounds) \
                    else float("inf")
        return float("inf")


class _NullChild:
    """Shared no-op child for the disabled registry: zero allocation,
    zero state, accepts every child method."""
    __slots__ = ()

    def inc(self, n: float = 1.0):
        pass

    set = dec = observe = inc
    value = 0.0
    sum = 0.0
    count = 0


_NULL_CHILD = _NullChild()


class Metric:
    """One named metric family; ``labels(**kv)`` returns the cached
    child for that label combination (create-once under the registry
    lock, then lock-free)."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Tuple[str, ...],
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = labelnames
        self.buckets = tuple(buckets) if buckets else None
        self._children: Dict[Tuple[str, ...], object] = {}
        self._lock = threading.Lock()

    def _new_child(self):
        if self.kind == "counter":
            return _CounterChild()
        if self.kind == "gauge":
            return _GaugeChild()
        return _HistChild(self.buckets or DEFAULT_MS_BUCKETS)

    def labels(self, **kv):
        key = tuple(str(kv.get(n, "")) for n in self.labelnames)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._new_child())
        return child

    # no-label conveniences: metric acts as its own single child
    def inc(self, n: float = 1.0):
        self.labels().inc(n)

    def set(self, v: float):
        self.labels().set(v)

    def observe(self, v: float):
        self.labels().observe(v)

    @property
    def value(self):
        return self.labels().value


class _NullMetric(Metric):
    def __init__(self):
        super().__init__("", "counter", "", ())

    def labels(self, **kv):
        return _NULL_CHILD

    def inc(self, n: float = 1.0):
        pass

    set = observe = inc


_NULL_METRIC = _NullMetric()


def _fmt_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    return repr(int(f)) if f == int(f) else repr(f)


def _escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash FIRST
    (escaping the escapes), then quote and newline."""
    return str(v).replace("\\", r"\\").replace('"', r'\"') \
        .replace("\n", r"\n")


def _escape_help(text: str) -> str:
    """HELP-line escaping (backslash + newline, per the exposition
    spec): a help string with a raw newline would split into a garbage
    non-comment line and break every scraper."""
    return str(text).replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = [(n, v) for n, v in zip(names, values)] + list(extra)
    if not pairs:
        return ""
    body = ",".join('%s="%s"' % (n, _escape_label_value(v))
                    for n, v in pairs)
    return "{" + body + "}"


class Registry:
    """Metric store.  One process-wide instance (``registry()``); tests
    may build private ones."""

    def __init__(self):
        self._metrics: Dict[str, Metric] = {}
        self._lock = threading.Lock()

    # ---- factories (get-or-create, kind-checked) ----------------------
    def _get(self, kind: str, name: str, help: str,
             labels: Sequence[str],
             buckets: Optional[Sequence[float]]) -> Metric:
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = Metric(name, kind, help, tuple(labels),
                               buckets=buckets)
                    self._metrics[name] = m
        if m.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {kind}")
        return m

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Metric:
        return self._get("counter", name, help, labels, None)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Metric:
        return self._get("gauge", name, help, labels, None)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Metric:
        return self._get("histogram", name, help, labels, buckets)

    # ---- export -------------------------------------------------------
    def exposition(self) -> str:
        """Prometheus text exposition (format 0.0.4)."""
        lines: List[str] = []
        for name in sorted(self._metrics):
            m = self._metrics[name]
            if not m._children:
                continue
            lines.append(f"# HELP {name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {name} {m.kind}")
            for key in sorted(m._children):
                c = m._children[key]
                if m.kind == "histogram":
                    acc = 0
                    bounds = list(c.bounds) + [float("inf")]
                    for b, n in zip(bounds, c.counts):
                        acc += n
                        lines.append(
                            f"{name}_bucket"
                            f"{_fmt_labels(m.labelnames, key, (('le', _fmt_value(b)),))}"
                            f" {acc}")
                    lines.append(
                        f"{name}_sum{_fmt_labels(m.labelnames, key)} "
                        f"{_fmt_value(c.sum)}")
                    lines.append(
                        f"{name}_count{_fmt_labels(m.labelnames, key)} "
                        f"{c.count}")
                else:
                    lines.append(
                        f"{name}{_fmt_labels(m.labelnames, key)} "
                        f"{_fmt_value(c.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> dict:
        """JSON-safe view: {metric: {"kind", "help", "series": [{labels,
        value | (sum,count,buckets)}]}} — the one-call train+serve+fleet
        answer the ISSUE asks for (everything feeds this registry)."""
        out = {}
        for name, m in self._metrics.items():
            series = []
            for key, c in m._children.items():
                labels = dict(zip(m.labelnames, key))
                if m.kind == "histogram":
                    series.append({
                        "labels": labels, "sum": round(c.sum, 6),
                        "count": c.count,
                        "buckets": dict(zip(
                            [_fmt_value(b) for b in c.bounds] + ["+Inf"],
                            c.counts)),
                    })
                else:
                    series.append({"labels": labels,
                                   "value": round(float(c.value), 6)})
            if series:
                out[name] = {"kind": m.kind, "help": m.help,
                             "series": series}
        return out

    # history lines kept when rewriting the snapshot file: bounds the
    # per-write cost (the rewrite is O(history), not O(all time)) and
    # the file itself.  PADDLE_TPU_METRICS_HISTORY overrides.
    _HISTORY_DEFAULT = 512

    def write_snapshot(self, path: str, extra: Optional[dict] = None
                       ) -> str:
        """Append one snapshot line to a JSONL history file ATOMICALLY:
        the retained history plus the new line land via fsync + tmp +
        rename, so a crash mid-write leaves the previous file intact
        and a reader never sees a torn line.  History is bounded (last
        ``PADDLE_TPU_METRICS_HISTORY`` lines, default 512) so periodic
        snapshotting stays O(bound) per write, and same-process writers
        are serialized by the registry lock; the path expects ONE
        writing process (last rename wins across processes)."""
        rec = {"ts": time.time(), **(extra or {}),
               "metrics": self.snapshot()}
        line = json.dumps(rec, default=str) + "\n"
        keep = int(os.environ.get("PADDLE_TPU_METRICS_HISTORY",
                                  self._HISTORY_DEFAULT)) - 1
        with self._lock:
            prior: List[str] = []
            try:
                with open(path) as f:
                    prior = f.readlines()
            except OSError:
                pass
            if keep >= 0 and len(prior) > keep:
                prior = prior[-keep:] if keep else []
            # size-based rotation on top of the line bound
            # (PADDLE_TPU_METRICS_SNAPSHOT_MAX_MB, default 64): a
            # week-long serve run snapshotting fat label sets must not
            # grow the file unbounded — drop oldest lines until the
            # rewrite fits; the NEW line always lands even if it alone
            # exceeds the budget (current state beats history)
            try:
                max_mb = float(os.environ.get(
                    "PADDLE_TPU_METRICS_SNAPSHOT_MAX_MB", 64))
            except ValueError:
                max_mb = 64.0
            if max_mb > 0:
                budget = max_mb * 1e6 - len(line)
                total = sum(len(p) for p in prior)
                while prior and total > budget:
                    total -= len(prior.pop(0))
            from ..framework.fs import open_for_write
            with open_for_write(path, "w") as f:
                f.write("".join(prior) + line)
        return path

    def clear(self):
        """Drop every metric (test isolation)."""
        with self._lock:
            self._metrics.clear()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str, help: str = "",
            labels: Sequence[str] = ()) -> Metric:
    if not metrics_enabled():
        return _NULL_METRIC
    return _REGISTRY.counter(name, help, labels)


def gauge(name: str, help: str = "", labels: Sequence[str] = ()) -> Metric:
    if not metrics_enabled():
        return _NULL_METRIC
    return _REGISTRY.gauge(name, help, labels)


def histogram(name: str, help: str = "", labels: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Metric:
    if not metrics_enabled():
        return _NULL_METRIC
    return _REGISTRY.histogram(name, help, labels, buckets=buckets)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def write_snapshot(path: Optional[str] = None,
                   extra: Optional[dict] = None) -> Optional[str]:
    """Write a snapshot line to `path` (default: the PADDLE_TPU_METRICS
    env when it names a file path).  Returns the path, or None when
    there is nowhere to write."""
    if path is None:
        env = os.environ.get("PADDLE_TPU_METRICS", "")
        path = env if env not in ("", "0", "1") else None
    if not path:
        return None
    return _REGISTRY.write_snapshot(path, extra=extra)


# ---------------------------------------------------------------------------
# exposition parser (the bench smoke's round-trip proof)
# ---------------------------------------------------------------------------
def _parse_labels(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    i = 0
    while i < len(text):
        eq = text.index("=", i)
        name = text[i:eq].strip().lstrip(",").strip()
        assert text[eq + 1] == '"', f"unquoted label value at {text!r}"
        j = eq + 2
        buf = []
        while text[j] != '"':
            if text[j] == "\\":
                nxt = text[j + 1]
                buf.append({"n": "\n", "\\": "\\", '"': '"'}.get(nxt, nxt))
                j += 2
            else:
                buf.append(text[j])
                j += 1
        out[name] = "".join(buf)
        i = j + 1
    return out


def parse_exposition(text: str) -> dict:
    """Parse Prometheus text exposition back into
    ``{name: {"type": ..., "samples": [(labels dict, value)]}}`` —
    raises on malformed lines, which is exactly what the smoke wants."""
    out: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(None, 3)
            types[name] = kind
            out.setdefault(name, {"type": kind, "samples": []})
            continue
        if line.startswith("#"):
            continue
        # sample line: name[{labels}] value
        if "{" in line:
            name = line[:line.index("{")]
            rest = line[line.index("{") + 1:]
            lbl_text = rest[:rest.rindex("}")]
            val_text = rest[rest.rindex("}") + 1:].strip()
            labels = _parse_labels(lbl_text)
        else:
            name, val_text = line.split(None, 1)
            labels = {}
        value = float("inf") if val_text == "+Inf" else float(val_text)
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in types \
                    and types[name[:-len(suffix)]] == "histogram":
                base = name[:-len(suffix)]
        out.setdefault(base, {"type": types.get(base, "untyped"),
                              "samples": []})
        out[base]["samples"].append((name, labels, value))
    return out
