"""Fleet aggregation + rolling SLO watch.

The Router places requests; this module answers "is the fleet healthy":

- :class:`FleetAggregator` scrapes replica metric surfaces
  (``engine.stats`` / consumed per-request records) into FLEET-level
  registry metrics — one TTFT histogram and token/request counters
  labeled per replica, plus queue-depth / block-occupancy gauges — so
  one ``metrics.snapshot()`` (or a Prometheus scrape) answers for the
  whole fleet.
- :class:`SLOMonitor` keeps a rolling window of per-request TTFTs and
  flags (a) threshold breaches (p99 over the target) and (b)
  REGRESSIONS against the bench history: ``BENCH_rows.jsonl`` rows are
  the measured record of what this host could do — a live p99 far above
  the best measured row means the deployment degraded, not the load.

Everything here is host-side dict reading — no device state, no syncs —
so a monitor tick is safe inside a serving loop.
"""
from __future__ import annotations

import json
import os
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import metrics

__all__ = ["FleetAggregator", "SLOMonitor", "load_bench_baseline"]


class FleetAggregator:
    """Pull each replica's request records into fleet registry metrics.

    ``scrape()`` consumes NEW finished-request records since the last
    scrape (tracked by rid — records themselves stay in the engine's
    bounded history for the load harness) and refreshes per-replica
    load gauges.  Optionally feeds an :class:`SLOMonitor`."""

    def __init__(self, replicas: Sequence, monitor:
                 Optional["SLOMonitor"] = None):
        self.replicas = list(replicas)
        self.monitor = monitor
        self._seen: List[set] = [set() for _ in self.replicas]
        self._m_ttft = metrics.histogram(
            "fleet_ttft_ms", "per-request time to first token",
            labels=("replica",))
        self._m_tokens = metrics.counter(
            "fleet_tokens_total", "generated tokens", labels=("replica",))
        self._m_requests = metrics.counter(
            "fleet_requests_total", "finished requests",
            labels=("replica", "outcome"))
        self._m_queue = metrics.gauge(
            "fleet_queue_depth", "queued + active requests",
            labels=("replica",))
        self._m_blocks = metrics.gauge(
            "fleet_kv_blocks_in_use", "paged KV blocks in use",
            labels=("replica",))
        self._m_tick_ms = metrics.gauge(
            "fleet_tick_ms", "mean decode-tick wall time per replica",
            labels=("replica",))

    def _tick_ms(self) -> List[Optional[float]]:
        """Per-replica mean decode-tick wall time (engine lifetime);
        None for replicas without timing surfaces or with no ticks."""
        out: List[Optional[float]] = []
        for r in self.replicas:
            t = getattr(r, "_timings", None)
            if not isinstance(t, dict) or not t.get("decode_steps") \
                    or not isinstance(t.get("decode_ms"), (int, float)):
                out.append(None)
                continue
            out.append(t["decode_ms"] / t["decode_steps"])
        return out

    def stragglers(self) -> dict:
        """Tick-time skew vs the fleet median (watchdog.
        detect_stragglers over the replicas' live timing surfaces)."""
        from .watchdog import detect_stragglers
        return detect_stragglers(self._tick_ms())

    def scrape(self) -> dict:
        """One aggregation pass; returns {"new_requests": n,
        "straggler": <detect_stragglers verdict>}."""
        new = 0
        for i, r in enumerate(self.replicas):
            lbl = str(i)
            # remote replicas (router.RPCReplicaProxy) expose cached
            # snapshots — pull a fresh one before reading them
            refresh = getattr(r, "refresh_stats", None)
            if callable(refresh):
                refresh()
            seen = self._seen[i]
            for rid, rec in list(r.request_stats.items()):
                if rid in seen:
                    continue
                seen.add(rid)
                new += 1
                ttft = rec.get("ttft_ms")
                if ttft is not None:
                    self._m_ttft.labels(replica=lbl).observe(ttft)
                    if self.monitor is not None:
                        self.monitor.observe(ttft)
                self._m_tokens.labels(replica=lbl).inc(
                    rec.get("tokens", 0))
                outcome = "timed_out" if rec.get("timed_out") else "ok"
                self._m_requests.labels(replica=lbl,
                                        outcome=outcome).inc()
            # bound the seen-set like the engine bounds request_stats
            if len(seen) > 2 * getattr(r, "_request_stats_cap", 4096):
                live = set(r.request_stats)
                self._seen[i] = seen & live
            q = len(getattr(r, "_queue", ())) + r.num_active
            self._m_queue.labels(replica=lbl).set(q)
            blocks = getattr(r, "blocks_in_use", None)
            if blocks is not None:
                self._m_blocks.labels(replica=lbl).set(blocks)
        tick_ms = self._tick_ms()
        for i, ms in enumerate(tick_ms):
            if ms is not None:
                self._m_tick_ms.labels(replica=str(i)).set(ms)
        from .watchdog import detect_stragglers
        return {"new_requests": new,
                "straggler": detect_stragglers(tick_ms)}


def load_bench_baseline(rows_path: Optional[str] = None,
                        kind: str = "loadtest",
                        field: str = "ttft_ms_p99") -> Optional[float]:
    """Best (lowest) measured `field` among non-smoke `kind` rows in the
    bench history file (default: BENCH_rows.jsonl next to bench.py —
    i.e. the repo root).  None when no usable row exists."""
    if rows_path is None:
        rows_path = os.environ.get("BENCH_ROWS_FILE", "").strip() or \
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))),
                "BENCH_rows.jsonl")
    best = None
    # a missing, empty, unreadable, or CORRUPT history file all mean
    # the same thing: no baseline.  Binary garbage raises
    # UnicodeDecodeError during line iteration (not json.loads), and a
    # monitor constructed inside a serving loop must never die on it.
    try:
        with open(rows_path, errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or rec.get("kind") != kind:
                    continue
                if "smoke" in str(rec.get("metric", "")):
                    continue            # smoke rows are not a perf record
                v = rec.get(field)
                if isinstance(v, (int, float)) and \
                        not isinstance(v, bool) and v > 0:
                    best = v if best is None else min(best, v)
    except (OSError, ValueError):
        return None
    return best


class SLOMonitor:
    """Rolling TTFT watch: threshold breaches + bench-history regression.

    observe() per finished request (FleetAggregator feeds it); check()
    computes the window p50/p99 and returns breach flags.  Cheap enough
    to call every scrape — percentiles over a bounded deque."""

    def __init__(self, ttft_p99_ms: Optional[float] = None,
                 window: int = 512,
                 regression_factor: float = 2.0,
                 baseline_ttft_p99_ms: Optional[float] = None,
                 rows_path: Optional[str] = None):
        env = os.environ.get("PADDLE_TPU_SLO_TTFT_P99_MS", "").strip()
        if ttft_p99_ms is None and env:
            ttft_p99_ms = float(env)
        self.ttft_p99_ms = ttft_p99_ms
        self.regression_factor = float(regression_factor)
        if baseline_ttft_p99_ms is None:
            baseline_ttft_p99_ms = load_bench_baseline(rows_path)
        self.baseline_ttft_p99_ms = baseline_ttft_p99_ms
        self._window: deque = deque(maxlen=int(window))
        self.breaches = 0
        self.regressions = 0
        # verdict listeners (ISSUE 16): every check() verdict is pushed
        # to subscribers — the live autotune retuner's signal feed.  A
        # listener exception must never take the serving loop down.
        self._listeners: List = []
        self._g_p99 = metrics.gauge("slo_ttft_ms_p99",
                                    "rolling-window TTFT p99")
        self._g_p50 = metrics.gauge("slo_ttft_ms_p50",
                                    "rolling-window TTFT p50")
        self._c_breach = metrics.counter(
            "slo_breaches_total", "rolling p99 over target",
            labels=("kind",))

    def observe(self, ttft_ms: float):
        self._window.append(float(ttft_ms))

    def check(self) -> dict:
        """Evaluate the window; returns the verdict dict and updates the
        registry gauges/counters."""
        out: Dict[str, object] = {
            "window": len(self._window),
            "ttft_p99_target_ms": self.ttft_p99_ms,
            "baseline_ttft_p99_ms": self.baseline_ttft_p99_ms,
            "p50_ms": None, "p99_ms": None,
            "breached": False, "regressed": False,
        }
        if not self._window:
            return out
        p50, p99 = np.percentile(list(self._window), [50, 99])
        out["p50_ms"] = round(float(p50), 3)
        out["p99_ms"] = round(float(p99), 3)
        self._g_p50.set(float(p50))
        self._g_p99.set(float(p99))
        if self.ttft_p99_ms is not None and p99 > self.ttft_p99_ms:
            out["breached"] = True
            self.breaches += 1
            self._c_breach.labels(kind="threshold").inc()
        if self.baseline_ttft_p99_ms is not None and \
                p99 > self.baseline_ttft_p99_ms * self.regression_factor:
            out["regressed"] = True
            self.regressions += 1
            self._c_breach.labels(kind="regression").inc()
        for cb in self._listeners:
            try:
                cb(out)
            except Exception:
                pass
        return out

    def add_listener(self, cb) -> "SLOMonitor":
        """Subscribe ``cb(verdict_dict)`` to every check() result (e.g.
        a LiveRetuner's ``notify_slo``). Returns self for chaining."""
        self._listeners.append(cb)
        return self
