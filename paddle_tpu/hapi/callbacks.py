"""Training callbacks (reference python/paddle/hapi/callbacks.py:130 —
Callback, CallbackList, ProgBarLogger, ModelCheckpoint, LRScheduler,
EarlyStopping; VisualDL is replaced by a plain history recorder)."""
from __future__ import annotations

import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
           "LRScheduler", "EarlyStopping", "History", "config_callbacks"]


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params or {}

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = list(callbacks)

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for cb in self.callbacks:
            cb.set_params(params)

    def set_model(self, model):
        for cb in self.callbacks:
            cb.set_model(model)

    def on_begin(self, mode, logs=None):
        for cb in self.callbacks:
            getattr(cb, f"on_{mode}_begin")(logs)

    def on_end(self, mode, logs=None):
        for cb in self.callbacks:
            getattr(cb, f"on_{mode}_end")(logs)

    def on_epoch_begin(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for cb in self.callbacks:
            getattr(cb, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for cb in self.callbacks:
            getattr(cb, f"on_{mode}_batch_end")(step, logs)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self._t0 = time.time()

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0

    @staticmethod
    def _format_logs(logs):
        """Format scalar-ish log values; lazy device scalars
        (StepResult/LazyValue) are forced here — printing IS the sync
        point, and it only happens at log_freq boundaries."""
        items = []
        for k, v in (logs or {}).items():
            if k == "batch_size" or isinstance(v, bool):
                continue
            try:
                items.append(f"{k}: {float(v):.4f}")
            except (TypeError, ValueError):
                continue
        return " - ".join(items)

    def on_train_batch_end(self, step, logs=None):
        self.steps += 1
        if self.verbose and step % self.log_freq == 0:
            print(f"Epoch {self.epoch} step {step}: "
                  f"{self._format_logs(logs)}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            print(f"Epoch {epoch} done: {self._format_logs(logs)}")


class History(Callback):
    def __init__(self):
        super().__init__()
        self.history = {}

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best_model = save_best_model
        if mode == "max" or (mode == "auto" and
                             ("acc" in monitor or "auc" in monitor)):
            self.is_better = lambda cur, best: cur > best + self.min_delta
            self.best = -np.inf
        else:
            self.is_better = lambda cur, best: cur < best - self.min_delta
            self.best = np.inf
        self.wait = 0

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            cur = (logs or {}).get("eval_" + self.monitor)
        if cur is None:
            return
        if self.is_better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait > self.patience:
                if self.model is not None:
                    self.model.stop_training = True
                if self.verbose:
                    print(f"Early stopping at epoch {epoch}")


def config_callbacks(callbacks=None, model=None, batch_size=None,
                     epochs=None, steps=None, log_freq=2, verbose=2,
                     save_freq=1, save_dir=None, metrics=None,
                     mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.insert(0, ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"batch_size": batch_size, "epochs": epochs,
                   "steps": steps, "verbose": verbose, "metrics": metrics})
    return cl
