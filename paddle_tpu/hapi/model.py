"""Model: prepare/fit/evaluate/predict/save/load.

Reference: python/paddle/hapi/model.py (Model:810 prepare, :1244 fit,
:1299 evaluate, :1515 predict; DynamicGraphAdapter:609). The static-graph
adapter is unnecessary — one eager loop covers both because to_static /
XLA compilation happens inside the layer when the user wants it.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric.metrics import Metric
from ..nn.layer_base import Layer
from . import callbacks as cbks_mod

__all__ = ["Model"]


class _Preempted(Exception):
    """Internal control flow: SIGTERM/SIGINT arrived, the in-flight step
    drained and a checkpoint committed — unwind fit() cleanly."""


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self.preempted = False
        self._mesh = None
        self._strategy = None
        self._trainer = None
        self._ckpt_manager = None
        # monotonic train-batch counter across resumes (names the eager
        # auto checkpoints so mid-epoch snapshots order correctly)
        self._global_batch_count = 0

    # ---- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, mesh=None, strategy=None):
        """reference hapi/model.py:810, extended the TPU-native way: pass
        mesh= (a jax.sharding.Mesh or {'dp': 8}-style dict) and/or
        strategy= (DistributedStrategy) and fit/evaluate/predict run the
        COMPILED SpmdTrainer step — the reference's CompiledProgram +
        ParallelExecutor chain (fleet_base.py:1066) collapsed into one
        XLA executable. Without them the eager per-op loop is used."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        # a re-prepare invalidates any trainer built for the old
        # optimizer/loss/mesh combination; pull its live arrays back into
        # the network first (the trainer's compiled step DONATES its
        # previous buffers, so the network's may already be deleted)
        if self._trainer is not None:
            self._trainer.sync_to_model()
        self._trainer = None
        self._mesh = None
        self._strategy = None
        # fleet.distributed_optimizer carries its strategy along
        strategy = strategy or getattr(optimizer, "user_defined_strategy",
                                       None)
        if mesh is not None or strategy is not None:
            from ..distributed.mesh import create_mesh, Mesh, default_mesh
            if isinstance(mesh, dict):
                mesh = create_mesh(mesh)
            self._mesh = mesh if mesh is not None else default_mesh()
            self._strategy = strategy
        return self

    @property
    def compiled(self) -> bool:
        return self._mesh is not None

    def _ensure_trainer(self):
        if self._trainer is not None:
            return self._trainer
        from ..distributed.spmd import SpmdTrainer
        if self._strategy is not None and self._strategy.pipeline:
            raise NotImplementedError(
                "strategy.pipeline in Model.fit: split the network with "
                "gpt_pipeline_parts-style stage views and use "
                "paddle_tpu.distributed.pipeline.GPipeTrainer directly")
        opt = getattr(self._optimizer, "inner_opt", self._optimizer)

        def loss_fn(outputs, *labels):
            outs = _to_list(outputs)
            return self._loss(*(outs + [self._t(l) for l in labels]))

        self._trainer = SpmdTrainer(self.network, opt, loss_fn,
                                    mesh=self._mesh,
                                    strategy=self._strategy)
        return self._trainer

    # ---- single-batch ops (reference Model.train_batch/eval_batch) -------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self.compiled and not update:
            raise NotImplementedError(
                "accumulate_grad_batches > 1 with a compiled Model: use "
                "strategy.gradient_merge (the accumulation then happens "
                "inside the compiled step with a dp-sharded buffer)")
        if self.compiled and update:
            # non-blocking dispatch: the StepResult (and lazy metric
            # accumulators) hold device values; nothing reads them back
            # here, so the host keeps queueing steps ahead of the device.
            # fit() forces them once per log_freq window; a direct caller
            # pays the sync at float(loss).
            tr = self._ensure_trainer()
            want_out = bool(self._metrics)
            if want_out:
                loss, outputs = tr.train_step(tuple(inputs), tuple(labels),
                                              return_outputs=True)
                out_t = [Tensor(o) for o in _to_list(outputs)]
                metrics = self._update_metrics(out_t, labels, lazy=True)
            else:
                loss = tr.train_step(tuple(inputs), tuple(labels))
                metrics = {}
            return ([loss], metrics) if metrics else [loss]
        self.network.train()
        outputs = self.network(*[self._t(i) for i in inputs])
        losses = self._compute_loss(outputs, labels)
        losses.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([float(losses)], metrics) if metrics else [float(losses)]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self.compiled:
            from ..distributed.async_dispatch import StepResult
            tr = self._ensure_trainer()
            outputs = [Tensor(o) for o in
                       _to_list(tr.eval_step(tuple(inputs)))]
            losses = self._compute_loss(outputs, labels) \
                if self._loss is not None else None
            metrics = self._update_metrics(outputs, labels, lazy=True)
            loss_list = [StepResult(losses, timings=tr._timings)] \
                if losses is not None else []
            return (loss_list, metrics) if metrics else loss_list
        self.network.eval()
        with no_grad():
            outputs = self.network(*[self._t(i) for i in inputs])
            losses = self._compute_loss(outputs, labels) \
                if self._loss is not None else None
        metrics = self._update_metrics(outputs, labels)
        loss_list = [float(losses)] if losses is not None else []
        return (loss_list, metrics) if metrics else loss_list

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        inputs = _to_list(inputs)
        if self.compiled:
            tr = self._ensure_trainer()
            return [Tensor(o) for o in
                    _to_list(tr.predict_step(tuple(inputs)))]
        self.network.eval()
        with no_grad():
            outputs = self.network(*[self._t(i) for i in inputs])
        return _to_list(outputs)

    def _t(self, x):
        if isinstance(x, Tensor):
            return x
        import jax
        if isinstance(x, jax.Array):
            # prefetched device array: wrap in place — np.asarray here
            # would be a per-batch host sync
            return Tensor(x)
        return Tensor(np.asarray(x))

    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        return self._loss(*(outs + labs))

    def _update_metrics(self, outputs, labels, lazy=False):
        """Run metric compute/update per batch. lazy=True (compiled
        mode) defers the accumulate() read-back behind a LazyValue so
        the step loop stays sync-free; readers (ProgBarLogger at
        log_freq, evaluate() at epoch end) force the CURRENT running
        value when they format it."""
        res = {}
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        for m in self._metrics:
            pre = m.compute(*(outs + labs))
            m.update(*_to_list(pre))
            key = m.name()[0] if isinstance(m.name(), list) else m.name()
            if lazy:
                from ..distributed.async_dispatch import LazyValue
                res[key] = LazyValue(m.accumulate)
            else:
                res[key] = m.accumulate()
        return res

    # ---- loops ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            auto_resume=False, prefetch_depth=None):
        """reference hapi/model.py:1244.

        Compiled mode runs a PIPELINED step loop: batches are
        device_put with the trainer's sharding by a background
        DevicePrefetcher (``prefetch_depth`` in flight, default 2 /
        ``PADDLE_TPU_PREFETCH_DEPTH``; 0 disables), and losses/metrics
        stay on device as lazy values that are read back at most once
        per ``log_freq`` steps — in between, the host only dispatches.

        auto_resume=True (with
        save_dir) checkpoints the FULL training state under
        save_dir/auto each save_freq epochs (asynchronously in compiled
        mode, with per-entry checksums) and, on restart, restores the
        newest VALID one — skipping truncated/corrupt snapshots — and
        continues from the recorded epoch/step. While auto_resume is
        active, SIGTERM/SIGINT drain the in-flight step, commit a final
        synchronous checkpoint (mid-epoch position included) and return
        cleanly, so the next launch resumes where the preemption hit —
        the reference's auto_checkpoint train_epoch_range semantics
        hardened for preemptible fleets."""
        train_loader = self._as_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=self._try_len(train_loader), log_freq=log_freq,
            save_freq=save_freq, save_dir=save_dir, verbose=verbose,
            metrics=self._metrics_names())
        start_epoch, skip_steps = 0, 0
        auto_dir = os.path.join(save_dir, "auto") \
            if (auto_resume and save_dir) else None
        guard = None
        if auto_dir:
            start_epoch, skip_steps = self._auto_restore(auto_dir)
            from ..distributed.resilience import PreemptionGuard
            guard = PreemptionGuard().install()
        if prefetch_depth is None:
            prefetch_depth = int(os.environ.get(
                "PADDLE_TPU_PREFETCH_DEPTH", "2"))
        self.stop_training = False
        self.preempted = False
        try:
            cbks.on_begin("train")
            for epoch in range(start_epoch, epochs):
                cbks.on_epoch_begin(epoch)
                try:
                    logs = self._run_one_epoch(
                        train_loader, cbks, "train",
                        accumulate_grad_batches, num_iters,
                        skip_steps=(skip_steps if epoch == start_epoch
                                    else 0),
                        guard=guard, epoch=epoch, auto_dir=auto_dir,
                        log_freq=log_freq, prefetch_depth=prefetch_depth)
                except _Preempted:
                    self.preempted = True
                    self.stop_training = True
                    break
                cbks.on_epoch_end(epoch, logs)
                if eval_loader is not None and \
                        (epoch + 1) % eval_freq == 0:
                    eval_logs = self.evaluate(eval_loader, verbose=0,
                                              callbacks=None,
                                              _inner_cbks=cbks)
                    logs.update({"eval_" + k: v
                                 for k, v in eval_logs.items()})
                if save_dir is not None and (epoch + 1) % save_freq == 0:
                    self.save(os.path.join(save_dir, str(epoch)))
                    if auto_dir:
                        self._auto_save(auto_dir, epoch)
                if self.stop_training:
                    break
            if save_dir is not None and not self.preempted:
                self.save(os.path.join(save_dir, "final"))
            cbks.on_end("train")
        finally:
            if guard is not None:
                guard.uninstall()
            if self._ckpt_manager is not None:
                self._ckpt_manager.wait()

    # ---- auto checkpoint (reference auto_checkpoint.py:71) ---------------
    _AUTO_KEEP = 2  # retained snapshots (newest + one fallback)

    def _ensure_ckpt_manager(self, auto_dir):
        from ..distributed.resilience import CheckpointManager
        if self._ckpt_manager is None or \
                self._ckpt_manager.directory != auto_dir:
            self._ckpt_manager = CheckpointManager(
                auto_dir, keep_last=self._AUTO_KEEP)
        return self._ckpt_manager

    def _eager_marker(self, auto_dir, epoch, batch_step, weights):
        """Eager-mode auto checkpoint: a JSON marker named by the global
        batch counter (monotonic across resumes) pointing at saved
        weights+optimizer state."""
        import json
        os.makedirs(auto_dir, exist_ok=True)
        g = self._global_batch_count
        tmp = os.path.join(auto_dir, f"ckpt-{g}.tmp")
        with open(tmp, "w") as f:
            json.dump({"epoch": epoch, "batch_step": batch_step,
                       "global_step": g, "mode": "eager",
                       "weights": weights}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(auto_dir, f"ckpt-{g}"))
        self._auto_prune(auto_dir)

    def _auto_save(self, auto_dir, epoch):
        if self.compiled:
            # async manifest checkpoint: the training thread pays only
            # the device->host snapshot; commit happens in the background
            tr = self._ensure_trainer()
            self._ensure_ckpt_manager(auto_dir).save(
                tr, step=tr._step_count, extra={"epoch": epoch})
        else:
            # eager: fit already wrote save_dir/{epoch}.pdparams/.pdopt
            # one line earlier — the auto marker just points at it
            weights = os.path.join(os.path.dirname(auto_dir), str(epoch))
            self._eager_marker(auto_dir, epoch, None, weights)

    def _preempt_save(self, auto_dir, epoch, step):
        """Final synchronous checkpoint on SIGTERM/SIGINT, carrying the
        mid-epoch position so resume skips the consumed batches."""
        if auto_dir is None:
            return
        if self.compiled:
            tr = self._ensure_trainer()
            self._ensure_ckpt_manager(auto_dir).save(
                tr, step=tr._step_count,
                extra={"epoch": epoch, "batch_step": step}, block=True)
        else:
            weights = os.path.join(os.path.dirname(auto_dir),
                                   f"preempt-{self._global_batch_count}")
            self.save(weights)
            self._eager_marker(auto_dir, epoch, step, weights)

    def _auto_prune(self, auto_dir):
        """Keep only the newest _AUTO_KEEP snapshots (the reference
        auto_checkpoint retains a bounded set)."""
        cks = []
        for name in os.listdir(auto_dir):
            if name.startswith("ckpt-") and not name.endswith(".tmp"):
                try:
                    cks.append((int(name[len("ckpt-"):]), name))
                except ValueError:
                    continue
        for _, name in sorted(cks)[:-self._AUTO_KEEP]:
            os.remove(os.path.join(auto_dir, name))

    def _auto_restore(self, auto_dir):
        """-> (start_epoch, skip_steps): restore the newest VALID auto
        checkpoint (manifest/checksum-verified for compiled snapshots;
        corrupt or truncated candidates fall back to the previous valid
        one). skip_steps > 0 means the checkpoint was taken mid-epoch
        (preemption): resume fast-forwards the loader past the batches
        already consumed."""
        import json
        from ..distributed.checkpoint import latest_checkpoint
        # validate=False: this lookup only decides compiled-vs-eager
        # from the candidate's TYPE; the actual restore below hashes and
        # falls back itself, so a full sha256 pass here would be a
        # redundant read of the whole checkpoint
        ck = latest_checkpoint(auto_dir, validate=False)
        if ck is None:
            return 0, 0
        # compiled snapshots are manifest DIRECTORIES (or legacy pickle
        # files); eager markers are JSON files
        if os.path.isdir(ck):
            ck_compiled = True
        else:
            with open(ck, "rb") as f:
                ck_compiled = f.read(1) == b"\x80"
        if ck_compiled != self.compiled:
            raise RuntimeError(
                f"auto checkpoint {ck} was written in "
                f"{'compiled' if ck_compiled else 'eager'} mode but this "
                f"run is {'compiled' if self.compiled else 'eager'}; "
                f"prepare() with the same mesh/strategy as the "
                f"interrupted run (or remove the auto/ directory)")
        if self.compiled:
            mgr = self._ensure_ckpt_manager(auto_dir)
            extra = mgr.restore_latest(self._ensure_trainer())
            if extra is None:
                return 0, 0
            epoch = int(extra.get("epoch", -1))
            batch_step = extra.get("batch_step")
            if batch_step is not None:
                return epoch, int(batch_step) + 1
            return epoch + 1, 0
        # eager: walk markers newest-first so a marker whose weights
        # vanished falls back instead of crashing
        cands = []
        for name in os.listdir(auto_dir):
            if name.startswith("ckpt-") and not name.endswith(".tmp"):
                try:
                    cands.append((int(name[len("ckpt-"):]), name))
                except ValueError:
                    continue
        for _, name in sorted(cands, reverse=True):
            try:
                with open(os.path.join(auto_dir, name)) as f:
                    meta = json.load(f)
                self.load(meta["weights"])
            except (OSError, ValueError, KeyError):
                continue
            self._global_batch_count = int(meta.get("global_step", 0))
            epoch = int(meta["epoch"])
            batch_step = meta.get("batch_step")
            if batch_step is not None:
                return epoch, int(batch_step) + 1
            return epoch + 1, 0
        return 0, 0

    @staticmethod
    def _resolve_logs(logs):
        """Force any lazy (device-resident) log values to concrete
        numbers — THE host sync point of the fit loop."""
        from ..distributed.async_dispatch import resolve
        for k, v in list(logs.items()):
            logs[k] = resolve(v)
        return logs

    def _run_one_epoch(self, loader, cbks, mode, accum=1, num_iters=None,
                       skip_steps=0, guard=None, epoch=0, auto_dir=None,
                       log_freq=10, prefetch_depth=0):
        from ..observability import metrics as _obs_metrics
        from ..profiler import StepTimer
        logs = {}
        timer = StepTimer(warmup=1)
        timer.start()
        # fit-loop wall time into the metrics registry (data + step —
        # the trainer's own train_step_time_ms excludes data); child
        # bound once, set per step
        m_step = _obs_metrics.gauge(
            "fit_step_time_ms",
            "hapi fit per-step wall time (data wait included)",
            labels=("mode",)).labels(mode=mode)
        for m in self._metrics:
            m.reset()
        it = iter(loader)
        first_step = 0
        if mode == "train" and skip_steps:
            # mid-epoch resume: these batches were consumed before the
            # preemption checkpoint — fast-forward past them ON THE HOST
            # (no device transfer) so the data order matches the
            # uninterrupted run
            for _ in range(skip_steps):
                try:
                    next(it)
                except StopIteration:
                    break
                first_step += 1
        prefetcher = None
        if mode == "train" and self.compiled and prefetch_depth > 0:
            # overlap host->device placement with compute: batches enter
            # train_batch already committed with the trainer's sharding.
            # Cap the source at num_iters FIRST so the prefetcher never
            # pulls (and discards) batches past the iteration budget —
            # a single-pass stream would lose them for the next epoch
            if num_iters is not None:
                import itertools
                it = itertools.islice(it, max(0, num_iters - first_step))
            from ..io.device_prefetch import DevicePrefetcher
            tr = self._ensure_trainer()
            prefetcher = DevicePrefetcher(it, tr.shard_batch,
                                          depth=prefetch_depth,
                                          timings=tr._timings)
            it = iter(prefetcher)
        try:
            for step, batch in enumerate(it, start=first_step):
                if num_iters is not None and step >= num_iters:
                    break
                cbks.on_batch_begin(mode, step, logs)
                ins, labs = self._split_batch(batch)
                update = (step + 1) % accum == 0
                if mode == "train":
                    out = self.train_batch(ins, labs, update=update)
                    self._global_batch_count += 1
                else:
                    out = self.eval_batch(ins, labs)
                if isinstance(out, tuple):
                    loss_list, metrics = out
                else:
                    loss_list, metrics = out, {}
                if loss_list:
                    logs["loss"] = loss_list[0]
                logs.update(metrics)
                logs["batch_size"] = (labs[0].shape[0] if labs else
                                      ins[0].shape[0])
                timer.tick()
                if timer.last_ms is not None:
                    # per-step wall time (reference profiler summary
                    # table); under async dispatch this is host-side
                    # time — the device view is stats["dispatch_ms"]
                    logs["step_time_ms"] = round(timer.last_ms, 3)
                    m_step.set(timer.last_ms)
                if step % log_freq == 0:
                    # the ONLY scheduled read-back: once per log window
                    self._resolve_logs(logs)
                cbks.on_batch_end(mode, step, logs)
                if mode == "train" and guard is not None and \
                        guard.preempted:
                    # the in-flight step has drained (train_batch
                    # returned): commit a final synchronous checkpoint
                    # and unwind
                    self._preempt_save(auto_dir, epoch, step)
                    raise _Preempted()
        finally:
            if prefetcher is not None:
                prefetcher.close()
        return self._resolve_logs(logs)

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 _inner_cbks=None):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        cbks = _inner_cbks or cbks_mod.config_callbacks(
            callbacks, model=self, steps=self._try_len(loader),
            log_freq=log_freq, verbose=verbose,
            metrics=self._metrics_names())
        if _inner_cbks is None:
            cbks.on_begin("eval")
        logs = self._run_one_epoch(loader, cbks, "eval",
                                   num_iters=num_iters, log_freq=log_freq)
        if _inner_cbks is None:
            cbks.on_end("eval", logs)
        out = {}
        if "loss" in logs:
            out["loss"] = logs["loss"]
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            out.update(dict(zip(names, vals)))
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            outputs.append([o.numpy() if isinstance(o, Tensor) else o
                            for o in outs])
        # transpose to per-output lists
        grouped = list(zip(*outputs))
        if stack_outputs:
            return [np.concatenate(g, axis=0) for g in grouped]
        return [list(g) for g in grouped]

    # ---- persistence (reference hapi/model.py:1043 save) ------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self._trainer is not None:
            # trainer owns the live arrays in compiled mode
            self._trainer.sync_to_model()
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))
        if self._trainer is not None:
            # compiled mode: the trainer owns the live arrays — adopt the
            # loaded weights or the restore would silently no-op
            self._trainer.sync_from_model()

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtypes=dtype)

    # ---- helpers ----------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _try_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _n_inputs(self):
        """Positional-arg count of network.forward (reference uses the
        _inputs spec for the same decision)."""
        import inspect
        try:
            sig = inspect.signature(self.network.forward)
            return len([p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty])
        except (TypeError, ValueError):
            return 1

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if len(batch) > 1:
                n_in = self._n_inputs()
                if has_labels:
                    n_in = min(n_in, len(batch) - 1)
                return batch[:n_in], (batch[n_in:] if has_labels else [])
            return batch, []
        return [batch], []

    def _metrics_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names
