"""Model: prepare/fit/evaluate/predict/save/load.

Reference: python/paddle/hapi/model.py (Model:810 prepare, :1244 fit,
:1299 evaluate, :1515 predict; DynamicGraphAdapter:609). The static-graph
adapter is unnecessary — one eager loop covers both because to_static /
XLA compilation happens inside the layer when the user wants it.
"""
from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..core.tensor import Tensor
from ..io import DataLoader
from ..metric.metrics import Metric
from ..nn.layer_base import Layer
from . import callbacks as cbks_mod

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        self._mesh = None
        self._strategy = None
        self._trainer = None

    # ---- setup ------------------------------------------------------------
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, mesh=None, strategy=None):
        """reference hapi/model.py:810, extended the TPU-native way: pass
        mesh= (a jax.sharding.Mesh or {'dp': 8}-style dict) and/or
        strategy= (DistributedStrategy) and fit/evaluate/predict run the
        COMPILED SpmdTrainer step — the reference's CompiledProgram +
        ParallelExecutor chain (fleet_base.py:1066) collapsed into one
        XLA executable. Without them the eager per-op loop is used."""
        self._optimizer = optimizer
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle.metric.Metric")
        # a re-prepare invalidates any trainer built for the old
        # optimizer/loss/mesh combination; pull its live arrays back into
        # the network first (the trainer's compiled step DONATES its
        # previous buffers, so the network's may already be deleted)
        if self._trainer is not None:
            self._trainer.sync_to_model()
        self._trainer = None
        self._mesh = None
        self._strategy = None
        # fleet.distributed_optimizer carries its strategy along
        strategy = strategy or getattr(optimizer, "user_defined_strategy",
                                       None)
        if mesh is not None or strategy is not None:
            from ..distributed.mesh import create_mesh, Mesh, default_mesh
            if isinstance(mesh, dict):
                mesh = create_mesh(mesh)
            self._mesh = mesh if mesh is not None else default_mesh()
            self._strategy = strategy
        return self

    @property
    def compiled(self) -> bool:
        return self._mesh is not None

    def _ensure_trainer(self):
        if self._trainer is not None:
            return self._trainer
        from ..distributed.spmd import SpmdTrainer
        if self._strategy is not None and self._strategy.pipeline:
            raise NotImplementedError(
                "strategy.pipeline in Model.fit: split the network with "
                "gpt_pipeline_parts-style stage views and use "
                "paddle_tpu.distributed.pipeline.GPipeTrainer directly")
        opt = getattr(self._optimizer, "inner_opt", self._optimizer)

        def loss_fn(outputs, *labels):
            outs = _to_list(outputs)
            return self._loss(*(outs + [self._t(l) for l in labels]))

        self._trainer = SpmdTrainer(self.network, opt, loss_fn,
                                    mesh=self._mesh,
                                    strategy=self._strategy)
        return self._trainer

    # ---- single-batch ops (reference Model.train_batch/eval_batch) -------
    def train_batch(self, inputs, labels=None, update=True):
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self.compiled and not update:
            raise NotImplementedError(
                "accumulate_grad_batches > 1 with a compiled Model: use "
                "strategy.gradient_merge (the accumulation then happens "
                "inside the compiled step with a dp-sharded buffer)")
        if self.compiled and update:
            tr = self._ensure_trainer()
            want_out = bool(self._metrics)
            if want_out:
                loss, outputs = tr.train_step(tuple(inputs), tuple(labels),
                                              return_outputs=True)
                out_t = [Tensor(o) for o in _to_list(outputs)]
                metrics = self._update_metrics(out_t, labels)
            else:
                loss = tr.train_step(tuple(inputs), tuple(labels))
                metrics = {}
            return ([float(loss)], metrics) if metrics else [float(loss)]
        self.network.train()
        outputs = self.network(*[self._t(i) for i in inputs])
        losses = self._compute_loss(outputs, labels)
        losses.backward()
        if update and self._optimizer is not None:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return ([float(losses)], metrics) if metrics else [float(losses)]

    def eval_batch(self, inputs, labels=None):
        from ..core.autograd import no_grad
        inputs = _to_list(inputs)
        labels = _to_list(labels)
        if self.compiled:
            tr = self._ensure_trainer()
            outputs = [Tensor(o) for o in
                       _to_list(tr.eval_step(tuple(inputs)))]
            losses = self._compute_loss(outputs, labels) \
                if self._loss is not None else None
            metrics = self._update_metrics(outputs, labels)
            loss_list = [float(losses)] if losses is not None else []
            return (loss_list, metrics) if metrics else loss_list
        self.network.eval()
        with no_grad():
            outputs = self.network(*[self._t(i) for i in inputs])
            losses = self._compute_loss(outputs, labels) \
                if self._loss is not None else None
        metrics = self._update_metrics(outputs, labels)
        loss_list = [float(losses)] if losses is not None else []
        return (loss_list, metrics) if metrics else loss_list

    def predict_batch(self, inputs):
        from ..core.autograd import no_grad
        inputs = _to_list(inputs)
        if self.compiled:
            tr = self._ensure_trainer()
            return [Tensor(o) for o in
                    _to_list(tr.predict_step(tuple(inputs)))]
        self.network.eval()
        with no_grad():
            outputs = self.network(*[self._t(i) for i in inputs])
        return _to_list(outputs)

    def _t(self, x):
        return x if isinstance(x, Tensor) else Tensor(np.asarray(x))

    def _compute_loss(self, outputs, labels):
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        return self._loss(*(outs + labs))

    def _update_metrics(self, outputs, labels):
        res = {}
        outs = _to_list(outputs)
        labs = [self._t(l) for l in labels]
        for m in self._metrics:
            pre = m.compute(*(outs + labs))
            m.update(*_to_list(pre))
            res[m.name()[0] if isinstance(m.name(), list) else m.name()] = \
                m.accumulate()
        return res

    # ---- loops ------------------------------------------------------------
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None,
            auto_resume=False):
        """reference hapi/model.py:1244. auto_resume=True (with
        save_dir) checkpoints the FULL training state under
        save_dir/auto each save_freq epochs and, on restart, restores
        the newest one and continues from the next epoch — the
        reference's auto_checkpoint train_epoch_range semantics."""
        train_loader = self._as_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None \
            else None
        cbks = cbks_mod.config_callbacks(
            callbacks, model=self, epochs=epochs,
            steps=self._try_len(train_loader), log_freq=log_freq,
            save_freq=save_freq, save_dir=save_dir, verbose=verbose,
            metrics=self._metrics_names())
        start_epoch = 0
        auto_dir = os.path.join(save_dir, "auto") \
            if (auto_resume and save_dir) else None
        if auto_dir:
            start_epoch = self._auto_restore(auto_dir)
        cbks.on_begin("train")
        self.stop_training = False
        for epoch in range(start_epoch, epochs):
            cbks.on_epoch_begin(epoch)
            logs = self._run_one_epoch(train_loader, cbks, "train",
                                       accumulate_grad_batches, num_iters)
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0,
                                          callbacks=None,
                                          _inner_cbks=cbks)
                logs.update({"eval_" + k: v for k, v in eval_logs.items()})
            if save_dir is not None and (epoch + 1) % save_freq == 0:
                self.save(os.path.join(save_dir, str(epoch)))
                if auto_dir:
                    self._auto_save(auto_dir, epoch)
            if self.stop_training:
                break
        if save_dir is not None:
            self.save(os.path.join(save_dir, "final"))
        cbks.on_end("train")

    # ---- auto checkpoint (reference auto_checkpoint.py:71) ---------------
    _AUTO_KEEP = 2  # retained snapshots (newest + one fallback)

    def _auto_save(self, auto_dir, epoch):
        if self.compiled:
            self._ensure_trainer().save(
                os.path.join(auto_dir, f"ckpt-{epoch}"),
                extra={"epoch": epoch})
        else:
            # eager: fit already wrote save_dir/{epoch}.pdparams/.pdopt
            # one line earlier — the auto marker just points at it
            import json
            os.makedirs(auto_dir, exist_ok=True)
            weights = os.path.join(os.path.dirname(auto_dir), str(epoch))
            tmp = os.path.join(auto_dir, f"ckpt-{epoch}.tmp")
            with open(tmp, "w") as f:
                json.dump({"epoch": epoch, "mode": "eager",
                           "weights": weights}, f)
            os.replace(tmp, os.path.join(auto_dir, f"ckpt-{epoch}"))
        self._auto_prune(auto_dir)

    def _auto_prune(self, auto_dir):
        """Keep only the newest _AUTO_KEEP snapshots (the reference
        auto_checkpoint retains a bounded set)."""
        cks = []
        for name in os.listdir(auto_dir):
            if name.startswith("ckpt-") and not name.endswith(".tmp"):
                try:
                    cks.append((int(name[len("ckpt-"):]), name))
                except ValueError:
                    continue
        for _, name in sorted(cks)[:-self._AUTO_KEEP]:
            os.remove(os.path.join(auto_dir, name))

    def _auto_restore(self, auto_dir) -> int:
        import json
        from ..distributed.checkpoint import latest_checkpoint
        ck = latest_checkpoint(auto_dir)
        if ck is None:
            return 0
        with open(ck, "rb") as f:
            is_pickle = f.read(1) == b"\x80"
        if is_pickle != self.compiled:
            raise RuntimeError(
                f"auto checkpoint {ck} was written in "
                f"{'compiled' if is_pickle else 'eager'} mode but this "
                f"run is {'compiled' if self.compiled else 'eager'}; "
                f"prepare() with the same mesh/strategy as the "
                f"interrupted run (or remove the auto/ directory)")
        if self.compiled:
            extra = self._ensure_trainer().load(ck)
            return int(extra.get("epoch", -1)) + 1
        with open(ck) as f:
            meta = json.load(f)
        self.load(meta["weights"])
        return int(meta["epoch"]) + 1

    def _run_one_epoch(self, loader, cbks, mode, accum=1, num_iters=None):
        from ..profiler import StepTimer
        logs = {}
        timer = StepTimer(warmup=1)
        timer.start()
        for m in self._metrics:
            m.reset()
        for step, batch in enumerate(loader):
            if num_iters is not None and step >= num_iters:
                break
            cbks.on_batch_begin(mode, step, logs)
            ins, labs = self._split_batch(batch)
            update = (step + 1) % accum == 0
            if mode == "train":
                out = self.train_batch(ins, labs, update=update)
            else:
                out = self.eval_batch(ins, labs)
            if isinstance(out, tuple):
                loss_list, metrics = out
            else:
                loss_list, metrics = out, {}
            if loss_list:
                logs["loss"] = loss_list[0]
            logs.update(metrics)
            logs["batch_size"] = (labs[0].shape[0] if labs else
                                  ins[0].shape[0])
            timer.tick()
            if timer.last_ms is not None:
                # per-step wall time (reference profiler summary table)
                logs["step_time_ms"] = round(timer.last_ms, 3)
            cbks.on_batch_end(mode, step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None,
                 _inner_cbks=None):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        cbks = _inner_cbks or cbks_mod.config_callbacks(
            callbacks, model=self, steps=self._try_len(loader),
            log_freq=log_freq, verbose=verbose,
            metrics=self._metrics_names())
        if _inner_cbks is None:
            cbks.on_begin("eval")
        logs = self._run_one_epoch(loader, cbks, "eval",
                                   num_iters=num_iters)
        if _inner_cbks is None:
            cbks.on_end("eval", logs)
        out = {}
        if "loss" in logs:
            out["loss"] = logs["loss"]
        for m in self._metrics:
            names = m.name() if isinstance(m.name(), list) else [m.name()]
            vals = m.accumulate()
            vals = vals if isinstance(vals, list) else [vals]
            out.update(dict(zip(names, vals)))
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for batch in loader:
            ins, _ = self._split_batch(batch, has_labels=False)
            outs = self.predict_batch(ins)
            outputs.append([o.numpy() if isinstance(o, Tensor) else o
                            for o in outs])
        # transpose to per-output lists
        grouped = list(zip(*outputs))
        if stack_outputs:
            return [np.concatenate(g, axis=0) for g in grouped]
        return [list(g) for g in grouped]

    # ---- persistence (reference hapi/model.py:1043 save) ------------------
    def save(self, path, training=True):
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        if self._trainer is not None:
            # trainer owns the live arrays in compiled mode
            self._trainer.sync_to_model()
        from ..framework.io import save as fsave
        fsave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            fsave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io import load as fload
        self.network.set_state_dict(fload(path + ".pdparams"))
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))
        if self._trainer is not None:
            # compiled mode: the trainer owns the live arrays — adopt the
            # loaded weights or the restore would silently no-op
            self._trainer.sync_from_model()

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .model_summary import summary
        return summary(self.network, input_size, dtypes=dtype)

    # ---- helpers ----------------------------------------------------------
    def _as_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    @staticmethod
    def _try_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _n_inputs(self):
        """Positional-arg count of network.forward (reference uses the
        _inputs spec for the same decision)."""
        import inspect
        try:
            sig = inspect.signature(self.network.forward)
            return len([p for p in sig.parameters.values()
                        if p.kind in (p.POSITIONAL_ONLY,
                                      p.POSITIONAL_OR_KEYWORD)
                        and p.default is p.empty])
        except (TypeError, ValueError):
            return 1

    def _split_batch(self, batch, has_labels=True):
        if isinstance(batch, (list, tuple)):
            batch = list(batch)
            if len(batch) > 1:
                n_in = self._n_inputs()
                if has_labels:
                    n_in = min(n_in, len(batch) - 1)
                return batch[:n_in], (batch[n_in:] if has_labels else [])
            return batch, []
        return [batch], []

    def _metrics_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names
